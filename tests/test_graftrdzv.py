"""graftrdzv (rendezvous protocol analysis, ISSUE 16): the PROTOCOL table
declared in runtime/rendezvous.py must load and match the extractor's view
of the module (writers, instants — no drift), the small-scope model checker
must prove the live protocol's invariants over 2-3-process worlds with
crash/wedge faults at every phase boundary AND catch each seeded protocol
mutation by the expected invariant, the G017-G019 rule families must trip
on their seeded fixtures while the clean twins (and the shipped tree) stay
quiet, and `graftscope conformance` must replay spooled rdzv_* instants
against the automaton with the documented exit statuses."""

import json
import pathlib

import pytest

from dynamic_load_balance_distributeddnn_tpu.analysis.flow import (
    CallGraph,
    Project,
    analyze_paths,
    check_conformance,
    extract_protocol,
    load_protocol,
    run_flow_rules,
    run_model_check,
)
from dynamic_load_balance_distributeddnn_tpu.analysis.flow.proto import (
    MUTATIONS,
    PROTO_DIR_TOKENS,
    RECOVERY_CORE,
    RECOVERY_ORDER,
    classify_protocol_file,
    rendezvous_source_path,
)
from dynamic_load_balance_distributeddnn_tpu.analysis.linter import lint_file
from dynamic_load_balance_distributeddnn_tpu.obs.scope_cli import (
    conformance,
    main as scope_main,
)
from dynamic_load_balance_distributeddnn_tpu.obs.spool import SpoolWriter
from dynamic_load_balance_distributeddnn_tpu.runtime.rendezvous import (
    RendezvousStateMachine,
    RendezvousTimeout,
)

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "graftflow"
REPO = pathlib.Path(__file__).resolve().parents[1]
PKG = REPO / "dynamic_load_balance_distributeddnn_tpu"


def codes(findings):
    return {f.code for f in findings}


@pytest.fixture(scope="module")
def repo_project():
    """One whole-package Project shared by the interprocedural tests."""
    return Project.load([str(p) for p in sorted(PKG.rglob("*.py"))])


# ------------------------------------------------------------ seeded fixtures


@pytest.mark.parametrize(
    "fixture,expected_code,min_findings",
    [
        # torn in-place protocol write + unguarded protocol read
        ("g017_violation.py", "G017", 2),
        # retire_runtime (phase 2) sequenced after establish (phase 3)
        ("g018_violation.py", "G018", 1),
        # unlocked mesh rebuild with a live staging thread, no quiesce
        ("g019_violation.py", "G019", 1),
        # ISSUE 18: pool allocator re-partitions ordinal→tenant map with a
        # live staging thread — no lock, no window quiesce
        ("g019_pool_violation.py", "G019", 1),
    ],
)
def test_rdzv_rule_trips_on_seeded_fixture(fixture, expected_code, min_findings):
    findings = analyze_paths([str(FIXTURES / fixture)])
    hits = [f for f in findings if f.code == expected_code]
    assert len(hits) >= min_findings, (fixture, findings)
    # a seeded fixture must not also trip unrelated flow rules (noise)
    assert codes(findings) == {expected_code}, findings
    # nor any single-file rule — each corpus file isolates ONE bug class
    assert lint_file(str(FIXTURES / fixture)) == []


@pytest.mark.parametrize(
    "fixture",
    [
        "g017_clean.py",
        "g018_clean.py",
        "g019_clean.py",
        "g019_pool_clean.py",
    ],
)
def test_rdzv_clean_fixture_is_quiet(fixture):
    path = str(FIXTURES / fixture)
    assert analyze_paths([path]) == []
    assert lint_file(path) == []


def test_g017_flags_both_sides_of_the_file_discipline():
    """ISSUE contract: the raw in-place write AND the unguarded read each
    get their own finding — write discipline and read tolerance are
    separate obligations."""
    findings = analyze_paths([str(FIXTURES / "g017_violation.py")])
    by_line = {f.line: f.message for f in findings}
    assert any("os.replace" in m for m in by_line.values()), findings
    assert any("torn" in m and "read" in m for m in by_line.values()), findings
    assert {f.symbol.split("::")[-1] for f in findings} == {
        "offer_join",
        "read_roster",
    }


def test_g018_names_the_inverted_phases():
    (finding,) = analyze_paths([str(FIXTURES / "g018_violation.py")])
    assert "retire_runtime" in finding.message
    assert "establish" in finding.message
    assert "phase 2" in finding.message and "phase 3" in finding.message


def test_g019_flags_the_pre_fix_reshard_shape():
    """The in-tree incident shape: `_reshard_world` used to rewrite the
    topology with only a program-order argument while engine threads ran."""
    (finding,) = analyze_paths([str(FIXTURES / "g019_violation.py")])
    assert "self.mesh" in finding.message
    assert "quiesce" in finding.message or "drain" in finding.message


# -------------------------------------------------------- protocol extraction


def test_protocol_table_loads_from_rendezvous_source():
    proto = load_protocol()
    assert proto["version"] >= 1
    assert set(proto["files"]) == {
        "ack", "propose", "torn", "loss", "join", "done", "probe", "rebuild",
    }
    assert proto["phases"] == (
        "running", "agree", "teardown", "establish", "established",
    )
    # every declared instant maps to a declared phase (or the wildcard)
    for name, phase in proto["instants"].items():
        assert name.startswith("rdzv_")
        assert phase == "*" or phase in proto["phases"], name
    # the rule-side constants are literal copies of the table's — drift
    # between the checker and the declaration is itself a bug
    assert dict(proto["recovery_order"]) == RECOVERY_ORDER
    assert set(proto["recovery_core"]) == set(RECOVERY_CORE)
    assert set(proto["dir_tokens"]) <= PROTO_DIR_TOKENS


def test_classify_protocol_file_matches_declared_patterns():
    proto = load_protocol()
    assert classify_protocol_file("ack_g3.json", proto) == "ack"
    assert classify_protocol_file("propose_g11_r0_p2.json", proto) == "propose"
    assert classify_protocol_file("join_p0.json", proto) == "join"
    assert classify_protocol_file("postmortem.trace.json", proto) is None


def test_extractor_agrees_with_the_declared_table(repo_project):
    """No drift: every declared writer/instant is observed in the module
    source and vice versa — the mismatch list the G017 rule would report
    on runtime/rendezvous.py itself is empty."""
    model = extract_protocol(repo_project)
    assert model is not None
    assert model.mismatches == [], model.mismatches
    # the coordinator ack write and the shared loss-claim write were both
    # attributed to functions that write protocol files
    assert any(model.writers.get(kind) for kind in ("ack", "loss")), (
        model.writers
    )
    assert pathlib.Path(rendezvous_source_path()).name == "rendezvous.py"


def test_extractor_is_none_on_trees_without_a_rendezvous_module():
    proj = Project.load([str(FIXTURES / "g017_clean.py")])
    assert extract_protocol(proj) is None


# ----------------------------------------------- small-scope model checking


@pytest.mark.parametrize(
    "scenario,kwargs",
    [
        # two steady-state procs, faults at every recovery phase boundary
        ("steady2", dict(n_procs=2, budget=2)),
        # three procs: concurrent recoveries, claim races, roster splits
        ("steady3", dict(n_procs=3, budget=2)),
        # cold bring-up over a dirty directory (stale gen-9 ack pre-seeded)
        ("bringup2", dict(n_procs=2, budget=0, stale=True)),
        # an established pair plus a cold joiner, one fault allowed
        ("join3", dict(n_procs=3, budget=1, joiner=True)),
    ],
)
def test_model_checker_proves_the_live_protocol(scenario, kwargs):
    result = run_model_check(**kwargs)
    assert result["violations"] == [], (scenario, result["violations"])
    assert result["deadlocks"] == 0, scenario
    assert result["states"] > 0


@pytest.mark.parametrize(
    "mutation,kwargs,invariant",
    [
        # drop the reset_rendezvous_dir wipe: the stale gen-9 ack survives
        # bring-up and gets adopted as if a live process had published it
        ("drop_reset_wipe", dict(n_procs=2, budget=0, stale=True),
         "stale-adoption"),
        # skip _reset_orbax_barrier_counters: a proc pairs into the new
        # generation with counters still keyed to the dead one
        ("skip_orbax_reset", dict(n_procs=2, budget=2), "orbax-reset"),
        # ignore published loss claims when dispatching collectives: a
        # ghost roster member wedges the op
        ("no_claim_adoption", dict(n_procs=3, budget=2), "claim-coherence"),
        # pair into the new world before every roster member retired the
        # old client — the establish-before-teardown reorder
        ("establish_before_teardown", dict(n_procs=3, budget=2),
         "teardown-barrier"),
    ],
)
def test_model_checker_catches_seeded_mutation(mutation, kwargs, invariant):
    assert mutation in MUTATIONS
    result = run_model_check(mutation=mutation, **kwargs)
    assert result["violations"], (mutation, "mutation survived the checker")
    assert any(v.startswith(invariant) for v in result["violations"]), (
        mutation, result["violations"],
    )


def test_mutation_catalogue_is_exercised():
    """Every seeded mutation the checker knows about has a test above —
    adding a mutation without a catch assertion must fail loudly."""
    assert set(MUTATIONS) == {
        "drop_reset_wipe",
        "skip_orbax_reset",
        "no_claim_adoption",
        "establish_before_teardown",
    }


def test_unknown_mutation_is_rejected():
    with pytest.raises(ValueError, match="unknown mutation"):
        run_model_check(2, mutation="nonsense")


# ------------------------------------------------- multi-survivor rebuild vote


def test_rebuild_vote_settles_when_every_survivor_succeeds(tmp_path):
    """ISSUE 18 satellite: both survivors publish ok on the same attempt ->
    the round stands for BOTH of them (reading each other's files)."""
    a = RendezvousStateMachine(str(tmp_path), ident=0, gen=3)
    b = RendezvousStateMachine(str(tmp_path), ident=1, gen=3)
    a.rebuild_vote(0, ok=True)
    b.rebuild_vote(0, ok=True)
    assert a.rebuild_settled([0, 1], 0, timeout_s=5.0) is True
    assert b.rebuild_settled([0, 1], 0, timeout_s=5.0) is True


def test_rebuild_vote_any_failure_fails_the_round_for_everyone(tmp_path):
    a = RendezvousStateMachine(str(tmp_path), ident=0, gen=3)
    b = RendezvousStateMachine(str(tmp_path), ident=1, gen=3)
    a.rebuild_vote(1, ok=True)
    b.rebuild_vote(1, ok=False)
    # the locally-successful survivor learns its peer failed -> retries too
    assert a.rebuild_settled([0, 1], 1, timeout_s=5.0) is False
    assert b.rebuild_settled([0, 1], 1, timeout_s=5.0) is False
    # attempts are independent rounds: round 1's verdict does not leak
    a.rebuild_vote(2, ok=True)
    b.rebuild_vote(2, ok=True)
    assert a.rebuild_settled([0, 1], 2, timeout_s=5.0) is True


def test_rebuild_vote_missing_peer_times_out(tmp_path):
    """A survivor that aborted without voting must not hang its peers
    forever: the wait degrades into a RendezvousTimeout -> abort-and-resume."""
    a = RendezvousStateMachine(str(tmp_path), ident=0, gen=3)
    a.rebuild_vote(0, ok=True)
    with pytest.raises(RendezvousTimeout, match="rebuild-vote"):
        a.rebuild_settled([0, 1], 0, timeout_s=0.3)


# ------------------------------------------------------- shipped-tree hygiene


def test_shipped_tree_is_clean_under_g017_g019(repo_project):
    findings = [
        f
        for f in run_flow_rules(repo_project, select=["G017", "G018", "G019"])
    ]
    assert findings == [], findings


def test_thread_inventory_covers_the_recorder_and_rendezvous_threads(
    repo_project,
):
    """ISSUE 16 satellite: the G012 thread inventory must see the spool
    flusher, the rdzv drain worker, and the heartbeat watcher — the lock
    discipline of everything they touch is checked interprocedurally."""
    thread_fns = CallGraph(repo_project).thread_sides()[0]
    tails = {fn.rsplit("::", 1)[-1] for fn in thread_fns}
    assert "SpoolWriter._run" in tails, sorted(tails)
    assert "drain_collective_chain._drain" in tails
    assert "ProcessHeartbeat.watch._watch" in tails


# ------------------------------------------------------- trace conformance


def _inst(name, pid, ts, **args):
    return {"name": name, "ph": "i", "pid": pid, "tid": 1, "ts": ts,
            "args": args}


def _legal_recovery_events(roster=(0, 1), address="h0:9999"):
    evs = []
    for pid in roster:
        evs += [
            _inst("rdzv_init", pid, 10.0 + pid),
            _inst("rdzv_agreed", pid, 100.0 + pid, gen=1),
            _inst("rdzv_torn", pid, 200.0 + pid, gen=1),
            _inst("rdzv_established", pid, 300.0 + pid, gen=1,
                  roster=list(roster), address=address),
        ]
    return evs


def test_conformance_accepts_a_legal_recovery():
    violations, stats = check_conformance(_legal_recovery_events())
    assert violations == []
    assert stats["processes"] == [0, 1]
    assert stats["generations"] == [1]
    assert stats["counts"]["rdzv_established"] == 2


def test_conformance_tolerates_timeouts_and_unknown_instants():
    evs = _legal_recovery_events()
    evs.insert(2, _inst("rdzv_timeout", 0, 50.0, phase="collect"))
    evs.insert(0, _inst("rdzv_quarantine_rebuild", 1, 5.0))
    violations, _ = check_conformance(evs)
    assert violations == []


@pytest.mark.parametrize(
    "events,needle",
    [
        # establish skipped the teardown barrier entirely
        ([_inst("rdzv_established", 0, 1.0, gen=2, roster=[0], address="a")],
         "without passing the teardown barrier"),
        # teardown with no prior agreement for that generation
        ([_inst("rdzv_torn", 0, 1.0, gen=3)], "no prior agreement"),
        # generations must move strictly forward per process
        ([_inst("rdzv_agreed", 0, 1.0, gen=1),
          _inst("rdzv_torn", 0, 2.0, gen=1),
          _inst("rdzv_established", 0, 3.0, gen=1, roster=[0], address="a"),
          _inst("rdzv_agreed", 0, 4.0, gen=1)],
         "already established"),
        # the same generation established with divergent worlds
        ([_inst("rdzv_agreed", 0, 1.0, gen=1),
          _inst("rdzv_torn", 0, 2.0, gen=1),
          _inst("rdzv_established", 0, 3.0, gen=1, roster=[0, 1],
                address="a"),
          _inst("rdzv_agreed", 1, 1.5, gen=1),
          _inst("rdzv_torn", 1, 2.5, gen=1),
          _inst("rdzv_established", 1, 3.5, gen=1, roster=[0, 2],
                address="a")],
         "divergent worlds"),
    ],
)
def test_conformance_flags_illegal_traces(events, needle):
    violations, _ = check_conformance(events)
    assert any(needle in v for v in violations), (needle, violations)


# --------------------------------------------------------------- CLI surface


def _spool_with_instants(path, pid, instants):
    sp = SpoolWriter(str(path), pid=pid, ident=pid, base_unix=1000.0,
                     flush_interval_s=30.0)
    for name, ts, args in instants:
        sp.put((name, "rdzv", "i", ts, 0.0, 1, args))
    sp.close()


def _legal_spool_dir(tmp_path):
    for pid in (0, 1):
        _spool_with_instants(
            tmp_path / f"proc{pid}.{pid}.spool", pid,
            [
                ("rdzv_init", 10.0 + pid, None),
                ("rdzv_agreed", 100.0 + pid, {"gen": 1}),
                ("rdzv_torn", 200.0 + pid, {"gen": 1}),
                ("rdzv_established", 300.0 + pid,
                 {"gen": 1, "roster": [0, 1], "address": "h0:9999"}),
            ],
        )


def test_conformance_cli_passes_a_legal_spool_dir(tmp_path, capsys):
    _legal_spool_dir(tmp_path)
    assert scope_main(["conformance", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "conformance: OK" in out
    assert "rdzv_established" in out


def test_conformance_cli_json_reports_stats(tmp_path, capsys):
    _legal_spool_dir(tmp_path)
    assert scope_main(["conformance", str(tmp_path), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is True
    assert payload["violations"] == []
    assert payload["stats"]["counts"]["rdzv_agreed"] == 2


def test_conformance_cli_fails_on_a_violating_trace(tmp_path, capsys):
    _spool_with_instants(
        tmp_path / "proc0.0.spool", 0,
        [("rdzv_established", 50.0,
          {"gen": 2, "roster": [0], "address": "h0:1"})],
    )
    assert scope_main(["conformance", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "VIOLATION" in out and "teardown barrier" in out


def test_conformance_cli_reports_instant_free_spools_as_ok(tmp_path):
    _spool_with_instants(
        tmp_path / "proc0.0.spool", 0, [],
    )
    sp = SpoolWriter(str(tmp_path / "proc1.1.spool"), pid=1, ident=1,
                     base_unix=1000.0, flush_interval_s=30.0)
    sp.put(("train", "phase", "X", 0.0, 5.0, 1, {"epoch": 0}))
    sp.close()
    text, ok = conformance(str(tmp_path))
    assert ok
    assert "no rdzv_* instants" in text


def test_conformance_cli_empty_dir_is_a_usage_error(tmp_path, capsys):
    assert scope_main(["conformance", str(tmp_path)]) == 2
    err = capsys.readouterr().err
    assert "no readable spool/trace files" in err


def test_decisions_cli_empty_dir_is_a_usage_error(tmp_path, capsys):
    """Regression (ISSUE 16 satellite): `graftscope decisions` over an
    empty or missing directory used to print an empty journal and exit 0 —
    operators piping it into incident tooling read 'no decisions were
    made' where the truth was 'you pointed me at nothing'."""
    assert scope_main(["decisions", str(tmp_path)]) == 2
    err = capsys.readouterr().err
    assert "no readable spool/trace files" in err
    missing = tmp_path / "never_created"
    assert scope_main(["decisions", str(missing)]) == 2
