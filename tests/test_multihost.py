"""Multi-host integration: 2 processes × 2 virtual CPU devices, ws=4.

The analogue of the reference's localhost-gloo multi-process debug mode
(dbs.py:511-544, SURVEY §4.1) — here real separate OS processes rendezvous
through ``jax.distributed.initialize`` (gloo CPU collectives) and train with
the worker slice split across processes: elastic DBS path with a
deterministic 3:1 timing model, plus one fused (dbs-off) epoch over the
global mesh.

Asserts the replicated-controller contract: every process derives the
identical partition plan, and the plan shifts away from the slow worker.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # ~170s: real 2-process rendezvous + training

_WORKER = os.path.join(os.path.dirname(__file__), "_mh_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_cli_entry_point(tmp_path):
    """VERDICT r3 missing #3: the multi-host rendezvous must be reachable
    from the SHIPPED entry point — a 2-process CPU run launched via
    ``cli.main --coordinator ... --num_processes ... --process_id ...``
    (the reference launches via dbs.py:511-544)."""
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(os.path.dirname(__file__), "_mh_cli_worker.py")
    procs = [
        subprocess.Popen(
            [
                sys.executable, worker, str(i), "2", str(port),
                str(tmp_path / "logs"), str(tmp_path / "statis"),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=_REPO,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-4000:]}"
        assert "CLI_RC 0 nproc 2" in out, f"proc {i}:\n{out[-4000:]}"
    # rank-0 metric artifact written exactly once, by process 0
    stats = list((tmp_path / "statis").glob("*.npy"))
    assert len(stats) == 1, stats


def test_two_process_training():
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(i), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=_REPO,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-4000:]}"

    results = []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert lines, f"no RESULT line:\n{out[-4000:]}"
        results.append(json.loads(lines[-1][len("RESULT "):]))

    r0, r1 = sorted(results, key=lambda r: r["proc"])
    # Replicated controller: identical plan and metrics on every process.
    assert r0["shares"] == r1["shares"]
    assert r0["node_times"] == r1["node_times"]
    assert r0["losses"] == pytest.approx(r1["losses"], rel=1e-5)
    assert r0["fused_loss"] == pytest.approx(r1["fused_loss"], rel=1e-5)

    # The 3x-slower worker 0 ends with the smallest share, ~1/3 of the others.
    shares = np.asarray(r0["shares"])
    assert shares[0] == shares.min()
    assert shares[0] < 0.15
    # shares are rounded to 6 decimals in the worker's JSON
    assert abs(shares.sum() - 1.0) < 1e-5


def _spawn_rdzv_workers(tmp_path, n, port, env_extra=None, epochs=3, ws=4):
    """Launch ``n`` DBS_MH_RDZV workers logging to ``tmp_path/p<i>.log``.
    Returns (procs, log_paths, env)."""
    hb = tmp_path / "hb"
    ck = tmp_path / "ck"
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(
        DBS_MH_RDZV="1",
        DBS_PEER_HB_DIR=str(hb),
        DBS_MH_CKPT=str(ck),
        DBS_MH_EPOCHS=str(epochs),
        DBS_MH_WS=str(ws),
        DBS_PEER_HB_PERIOD_S="0.2",
        DBS_PEER_HB_STALE_S="2.0",
        DBS_RDZV_TIMEOUT_S="60",
    )
    env.update(env_extra or {})
    procs, logs = [], []
    for i in range(n):
        lp = tmp_path / f"p{i}.log"
        logs.append(lp)
        with open(lp, "w") as lf:
            procs.append(
                subprocess.Popen(
                    [sys.executable, _WORKER, str(i), str(n), str(port)],
                    stdout=lf,
                    stderr=subprocess.STDOUT,
                    env=env,
                    cwd=_REPO,
                )
            )
    return procs, logs, env


def _wait_for(path, procs, deadline_s=300, desc="marker"):
    deadline = time.time() + deadline_s
    while time.time() < deadline and not os.path.exists(str(path)):
        if all(p.poll() is not None for p in procs):
            return False
        time.sleep(0.1)
    return os.path.exists(str(path))


def _result_of(log_path):
    out = open(str(log_path)).read()
    lines = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
    assert lines, f"no RESULT line in {log_path}:\n{out[-4000:]}"
    return json.loads(lines[-1][len("RESULT "):])


def _kill_all(procs):
    for p in procs:
        try:
            p.kill()
            p.wait(timeout=30)
        except (OSError, ProcessLookupError):
            pass


def test_mh_kill_rerendezvous_resume_bitwise(tmp_path):
    """ISSUE 14 tentpole: a real 2-process run SURVIVES SIGKILL of one
    peer — the survivor detects the loss (collective-failure attribution +
    the watcher's detection marker), re-rendezvouses over the survivor
    set at the epoch boundary, restores the flushed checkpoint onto the
    reduced mesh and resumes with zero steady-state foreground compiles;
    the resumed trajectory is BITWISE-identical to a fresh reduced-world
    run restored from the same checkpoint."""
    port = _free_port()
    procs, logs, env = _spawn_rdzv_workers(tmp_path, 2, port, epochs=3)
    hb = tmp_path / "hb"
    try:
        assert _wait_for(
            hb / "epoch1_p1.marker", procs
        ), "fleet never reached epoch 1"
        procs[1].send_signal(signal.SIGKILL)
        rc0 = procs[0].wait(timeout=300)
        rc1 = procs[1].wait(timeout=30)
    finally:
        _kill_all(procs)
    assert rc1 == -signal.SIGKILL  # the kill was real
    out0 = open(str(logs[0])).read()
    assert rc0 == 0, f"survivor failed:\n{out0[-4000:]}"
    r = _result_of(logs[0])

    # survivor world: 2 workers over 1 process, ranks [2,3] gone
    assert r["world_size"] == 2 and r["n_proc"] == 1
    assert r["roster"] == [0]
    evs = r["elastic_events"]
    assert len(evs) == 1, evs
    ev = evs[0]
    assert ev["lost"] == [2, 3]
    assert ev["rdzv_gen"] == 1
    assert ev["restored_from"] == "checkpoint[0]"
    assert 0.0 < ev["detect_to_resume_s"] < 60.0
    # all three epochs trained (epoch 1 re-ran after the recovery)
    assert len(r["losses"]) == 3
    # zero steady-state foreground compiles after the re-warm
    assert r["xla_compiles"][-1] == 0
    # the watcher thread's detection marker (diagnosis survives even when
    # the collective's own failure was the first signal)
    assert (hb / "elastic_detected_proc1_by_proc0.json").exists()

    # ---- bitwise parity vs a fresh reduced-world run --------------------
    # A checkpoint-0-only copy (the live dir's LATEST step is the final
    # epoch — restoring it would be circular)
    import shutil

    ck, ckp = tmp_path / "ck", tmp_path / "ck_parity"
    ckp.mkdir()
    shutil.copytree(ck / "0", ckp / "0")
    shutil.copy(ck / "controller_0.json", ckp / "controller_0.json")
    penv = {
        k: v
        for k, v in env.items()
        if k not in ("DBS_MH_RDZV", "DBS_PEER_HB_DIR")
    }
    # the survivor-restricted sidecar — exactly what the survivor's
    # recovery adopts (_adopt_controller_vectors: survivor entries kept,
    # shares renormalized, node_times as-is)
    side = json.loads((ck / "controller_0.json").read_text())
    sh = [side["shares"][r] for r in (0, 1)]
    penv.update(
        DBS_MH_PARITY="1",
        DBS_MH_CKPT=str(ckp),
        DBS_MH_PARITY_VECS=json.dumps(
            {
                "shares": [s / sum(sh) for s in sh],
                "node_times": [side["node_times"][r] for r in (0, 1)],
            }
        ),
    )
    pp = subprocess.Popen(
        [sys.executable, _WORKER, "0", "1", str(port)],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=penv,
        cwd=_REPO,
    )
    pout, _ = pp.communicate(timeout=600)
    assert pp.returncode == 0, f"parity leg failed:\n{pout[-4000:]}"
    pr = json.loads(
        [ln for ln in pout.splitlines() if ln.startswith("RESULT ")][-1][
            len("RESULT "):
        ]
    )
    assert pr["start_epoch"] == 1  # resumed FROM checkpoint 0
    # bitwise: identical parameter bytes and identical post-recovery loss rows
    assert pr["params_hash"] == r["params_hash"]
    assert pr["losses"] == r["losses"][1:]


def test_mh_wedged_rendezvous_degrades_to_abort(tmp_path):
    """A rendezvous that cannot complete must DEGRADE, not hang: proc 2 is
    SIGKILLed, proc 1 is wedged (beacon alive, never reaches agree()) — the
    healthy survivor's propose phase times out and it falls back to today's
    abort-and-resume-from-checkpoint, logging the phase that died."""
    port = _free_port()
    procs, logs, _ = _spawn_rdzv_workers(
        tmp_path,
        3,
        port,
        epochs=3,
        ws=3,  # one worker per process
        env_extra={"DBS_MH_WEDGE": "1", "DBS_RDZV_TIMEOUT_S": "8"},
    )
    hb = tmp_path / "hb"
    try:
        assert _wait_for(
            hb / "epoch1_p2.marker", procs
        ), "fleet never reached epoch 1"
        procs[2].send_signal(signal.SIGKILL)
        t0 = time.time()
        rc0 = procs[0].wait(timeout=240)
        wall = time.time() - t0
    finally:
        _kill_all(procs)
    out0 = open(str(logs[0])).read()
    # nonzero abort, not a hang — and attributed to the rendezvous phase
    assert rc0 == 17, f"rc={rc0}:\n{out0[-4000:]}"
    assert wall < 200.0
    assert "degrading to abort-and-resume" in out0, out0[-4000:]
    assert "re-rendezvous FAILED in phase" in out0, out0[-4000:]


def test_mh_kill_shrink_respawn_regrow(tmp_path):
    """Satellite: the chaos round-trip — SIGKILL one peer (shrink), then
    respawn it as a JOINER (``DBS_MH_RESPAWNED=1``): it offers a rendezvous
    join, the survivor admits it at the next epoch boundary (grow), and
    both processes finish the run over the restored 4-worker fleet with
    IDENTICAL parameter bytes."""
    port = _free_port()
    procs, logs, env = _spawn_rdzv_workers(
        tmp_path,
        2,
        port,
        epochs=10,
        # stretch epochs so the joiner (full interpreter + jax import)
        # finds a boundary left to be admitted at
        env_extra={"DBS_MH_EPOCH_SLEEP_S": "3"},
    )
    hb = tmp_path / "hb"
    joiner = None
    try:
        assert _wait_for(
            hb / "epoch1_p1.marker", procs
        ), "fleet never reached epoch 1"
        procs[1].send_signal(signal.SIGKILL)
        # survivor reaches epoch 2 => the shrink rendezvous completed
        assert _wait_for(
            hb / "epoch2_p0.marker", [procs[0]]
        ), "survivor never resumed after the kill"
        jenv = dict(env)
        jenv.update(DBS_MH_RESPAWNED="1", DBS_MH_IDENT="1")
        jlog = tmp_path / "p1_respawn.log"
        with open(jlog, "w") as jf:
            joiner = subprocess.Popen(
                [sys.executable, _WORKER, "1", "2", str(port)],
                stdout=jf,
                stderr=subprocess.STDOUT,
                env=jenv,
                cwd=_REPO,
            )
        rc0 = procs[0].wait(timeout=400)
        rcj = joiner.wait(timeout=400)
    finally:
        _kill_all(procs + ([joiner] if joiner is not None else []))
    out0 = open(str(logs[0])).read()
    outj = open(str(jlog)).read()
    assert rc0 == 0, f"survivor failed:\n{out0[-4000:]}"
    assert rcj == 0, f"joiner failed:\n{outj[-4000:]}"
    r0, rj = _result_of(logs[0]), _result_of(jlog)

    # the grown world: 4 workers over both processes again, on BOTH sides
    for r in (r0, rj):
        assert r["world_size"] == 4 and r["n_proc"] == 2
        assert r["roster"] == [0, 1]
    # shrink then grow recorded on the survivor
    kinds = [
        ("lost" in ev, "readmitted" in ev) for ev in r0["elastic_events"]
    ]
    assert (True, False) in kinds and (False, True) in kinds, (
        r0["elastic_events"]
    )
    grow = next(ev for ev in r0["elastic_events"] if "readmitted" in ev)
    assert grow["readmitted"] == [2, 3]
    # both processes trained to the same parameters, bit for bit, and the
    # joiner's loss rows are the survivor's tail
    assert rj["params_hash"] == r0["params_hash"]
    assert rj["losses"] == r0["losses"][-len(rj["losses"]):]
    # steady state after the grow epoch is compile-free on the survivor
    grow_epoch = int(grow["epoch"])
    assert all(c == 0 for c in r0["xla_compiles"][grow_epoch + 1:])


def test_mh_sigkill_spool_postmortem(tmp_path):
    """ISSUE 15 acceptance: a REAL 2-process elastic run with the flight
    recorder on (`--trace ring --trace_spool`) where one peer is SIGKILLed
    mid-run. The victim's spool must survive its process (readable, torn
    tail tolerated) with its last events; `graftscope postmortem` over the
    spool directory must produce ONE merged pid-tagged Perfetto trace
    holding the victim's final evidence next to the survivor's rendezvous
    state-machine spans, plus the textual incident report."""
    from dynamic_load_balance_distributeddnn_tpu.obs.scope_cli import (
        postmortem,
    )
    from dynamic_load_balance_distributeddnn_tpu.obs.spool import read_spool

    port = _free_port()
    spool_dir = tmp_path / "spool"
    procs, logs, _env = _spawn_rdzv_workers(
        tmp_path, 2, port, epochs=3,
        env_extra={"DBS_MH_TRACE_SPOOL": str(spool_dir)},
    )
    hb = tmp_path / "hb"
    try:
        assert _wait_for(
            hb / "epoch1_p1.marker", procs
        ), "fleet never reached epoch 1"
        procs[1].send_signal(signal.SIGKILL)
        rc0 = procs[0].wait(timeout=300)
        rc1 = procs[1].wait(timeout=30)
    finally:
        _kill_all(procs)
    assert rc1 == -signal.SIGKILL
    out0 = open(str(logs[0])).read()
    assert rc0 == 0, f"survivor failed:\n{out0[-4000:]}"

    spools = {p.name.split(".")[0]: p for p in spool_dir.glob("*.spool")}
    assert set(spools) == {"proc0", "proc1"}, sorted(spool_dir.iterdir())
    # the victim's spool is readable WITHOUT its process: the background
    # flusher persisted its timeline up to the last flush interval
    victim = read_spool(str(spools["proc1"]))
    victim_events = [e for _, seg in victim["segments"] for e in seg]
    assert victim_events, "victim spool holds no events"
    assert victim["meta"]["ident"] == 1
    # it was training when it died: epoch-1 work is in the spooled tail
    names = {e[0] for e in victim_events}
    assert "epoch" in names or "dispatch_window" in names or "probe" in names

    report = json.loads(postmortem(str(spool_dir), as_json=True))
    merged_path = spool_dir / "postmortem.trace.json"
    assert str(merged_path) == report["trace"] and merged_path.exists()
    merged = json.loads(merged_path.read_text())
    evs = merged["traceEvents"]
    pids = {e.get("pid") for e in evs if e.get("ph") != "M"}
    assert len(pids) == 2, "merged trace must keep both processes' tracks"
    by_name = {e["name"] for e in evs}
    # the survivor's rendezvous state machine made it onto the timeline...
    assert {"rdzv_agree", "rdzv_establish"} <= by_name, sorted(by_name)[:40]
    assert "peer_lost" in by_name or "peer_stale" in by_name
    # ...and the victim's last events are in the SAME artifact
    victim_pid = int(victim["meta"]["pid"])
    assert any(
        e.get("pid") == victim_pid and e.get("ph") != "M" for e in evs
    )
    # the incident report narrates both processes
    procs_report = report["processes"]
    assert str(victim_pid) in procs_report
    surv = next(
        info for pid, info in procs_report.items() if int(pid) != victim_pid
    )
    span_names = {s["name"] for s in surv.get("recovery_spans", ())}
    assert {"rdzv_agree", "rdzv_establish"} <= span_names
    assert any(ev["name"] == "rdzv_agreed" for ev in report["timeline"])
    # ISSUE 16 acceptance: the same chaos spools replay clean against the
    # extracted protocol automaton — a real SIGKILL recovery is a LEGAL
    # trace, torn victim tail and all
    from dynamic_load_balance_distributeddnn_tpu.obs.scope_cli import (
        conformance,
    )

    text, ok = conformance(str(spool_dir))
    assert ok, f"chaos spools violate the rendezvous protocol:\n{text}"
    assert "rdzv_agreed" in text


def test_elastic_peer_loss_detection(tmp_path):
    """ISSUE 6 multi-host story: cross-process recovery is deliberately out
    of scope (a dead peer takes its mesh slice with it — README "Fault
    tolerance"), but a lost peer PROCESS must be *detected and diagnosed*,
    not silently hung on. Preempt one REAL worker process mid-run (SIGSTOP:
    the freeze case — no socket teardown races the detection the way a kill
    can); the survivor's peer watcher sees the stale heartbeat file and
    drops the detection marker from its watcher thread, even while its main
    thread is wedged in the collective against the frozen peer."""
    port = _free_port()
    hb_dir = tmp_path / "hb"
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["DBS_MH_ELASTIC"] = "1"
    env["DBS_PEER_HB_DIR"] = str(hb_dir)
    env["DBS_PEER_HB_PERIOD_S"] = "0.2"
    env["DBS_PEER_HB_STALE_S"] = "2.0"
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(i), "2", str(port)],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            env=env,
            cwd=_REPO,
        )
        for i in range(2)
    ]
    marker = hb_dir / "elastic_detected_proc1_by_proc0.json"
    try:
        deadline = time.time() + 300
        # beacons arm at Trainer construction (post-rendezvous)
        while time.time() < deadline and not (
            (hb_dir / "proc0.hb").exists() and (hb_dir / "proc1.hb").exists()
        ):
            if any(p.poll() is not None for p in procs):
                pytest.fail("a worker died before the beacons armed")
            time.sleep(0.2)
        assert (hb_dir / "proc1.hb").exists(), "beacons never armed"

        procs[1].send_signal(signal.SIGSTOP)  # the preemption freeze
        while time.time() < deadline and not marker.exists():
            time.sleep(0.2)
        assert marker.exists(), "survivor never detected the lost peer"
        info = json.loads(marker.read_text())
        assert info["peer"] == "proc1"
        assert "stale" in info["reason"] or "exit" in info["reason"]
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGCONT)
            except (OSError, ProcessLookupError):
                pass
            p.kill()
            p.wait(timeout=30)
