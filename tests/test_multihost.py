"""Multi-host integration: 2 processes × 2 virtual CPU devices, ws=4.

The analogue of the reference's localhost-gloo multi-process debug mode
(dbs.py:511-544, SURVEY §4.1) — here real separate OS processes rendezvous
through ``jax.distributed.initialize`` (gloo CPU collectives) and train with
the worker slice split across processes: elastic DBS path with a
deterministic 3:1 timing model, plus one fused (dbs-off) epoch over the
global mesh.

Asserts the replicated-controller contract: every process derives the
identical partition plan, and the plan shifts away from the slow worker.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # ~170s: real 2-process rendezvous + training

_WORKER = os.path.join(os.path.dirname(__file__), "_mh_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_cli_entry_point(tmp_path):
    """VERDICT r3 missing #3: the multi-host rendezvous must be reachable
    from the SHIPPED entry point — a 2-process CPU run launched via
    ``cli.main --coordinator ... --num_processes ... --process_id ...``
    (the reference launches via dbs.py:511-544)."""
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(os.path.dirname(__file__), "_mh_cli_worker.py")
    procs = [
        subprocess.Popen(
            [
                sys.executable, worker, str(i), "2", str(port),
                str(tmp_path / "logs"), str(tmp_path / "statis"),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=_REPO,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-4000:]}"
        assert "CLI_RC 0 nproc 2" in out, f"proc {i}:\n{out[-4000:]}"
    # rank-0 metric artifact written exactly once, by process 0
    stats = list((tmp_path / "statis").glob("*.npy"))
    assert len(stats) == 1, stats


def test_two_process_training():
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(i), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=_REPO,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-4000:]}"

    results = []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert lines, f"no RESULT line:\n{out[-4000:]}"
        results.append(json.loads(lines[-1][len("RESULT "):]))

    r0, r1 = sorted(results, key=lambda r: r["proc"])
    # Replicated controller: identical plan and metrics on every process.
    assert r0["shares"] == r1["shares"]
    assert r0["node_times"] == r1["node_times"]
    assert r0["losses"] == pytest.approx(r1["losses"], rel=1e-5)
    assert r0["fused_loss"] == pytest.approx(r1["fused_loss"], rel=1e-5)

    # The 3x-slower worker 0 ends with the smallest share, ~1/3 of the others.
    shares = np.asarray(r0["shares"])
    assert shares[0] == shares.min()
    assert shares[0] < 0.15
    # shares are rounded to 6 decimals in the worker's JSON
    assert abs(shares.sum() - 1.0) < 1e-5


def test_elastic_peer_loss_detection(tmp_path):
    """ISSUE 6 multi-host story: cross-process recovery is deliberately out
    of scope (a dead peer takes its mesh slice with it — README "Fault
    tolerance"), but a lost peer PROCESS must be *detected and diagnosed*,
    not silently hung on. Preempt one REAL worker process mid-run (SIGSTOP:
    the freeze case — no socket teardown races the detection the way a kill
    can); the survivor's peer watcher sees the stale heartbeat file and
    drops the detection marker from its watcher thread, even while its main
    thread is wedged in the collective against the frozen peer."""
    port = _free_port()
    hb_dir = tmp_path / "hb"
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["DBS_MH_ELASTIC"] = "1"
    env["DBS_PEER_HB_DIR"] = str(hb_dir)
    env["DBS_PEER_HB_PERIOD_S"] = "0.2"
    env["DBS_PEER_HB_STALE_S"] = "2.0"
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(i), "2", str(port)],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            env=env,
            cwd=_REPO,
        )
        for i in range(2)
    ]
    marker = hb_dir / "elastic_detected_proc1_by_proc0.json"
    try:
        deadline = time.time() + 300
        # beacons arm at Trainer construction (post-rendezvous)
        while time.time() < deadline and not (
            (hb_dir / "proc0.hb").exists() and (hb_dir / "proc1.hb").exists()
        ):
            if any(p.poll() is not None for p in procs):
                pytest.fail("a worker died before the beacons armed")
            time.sleep(0.2)
        assert (hb_dir / "proc1.hb").exists(), "beacons never armed"

        procs[1].send_signal(signal.SIGSTOP)  # the preemption freeze
        while time.time() < deadline and not marker.exists():
            time.sleep(0.2)
        assert marker.exists(), "survivor never detected the lost peer"
        info = json.loads(marker.read_text())
        assert info["peer"] == "proc1"
        assert "stale" in info["reason"] or "exit" in info["reason"]
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGCONT)
            except (OSError, ProcessLookupError):
                pass
            p.kill()
            p.wait(timeout=30)
