"""G001 seed: jit constructed in per-call scope and in a loop body.

``probe_workers`` reproduces the pre-fix form of engine.py's
``_probe_workers`` (the round-5 dispatch-overhead probe built a fresh
``jax.jit(lambda a: a + 1.0)`` wrapper every probe epoch, recompiling the
tiny op each time the closure identity changed)."""

import jax
import jax.numpy as jnp


def probe_workers(devices):
    # pre-fix engine.py:1478: fresh wrapper (and XLA cache entry) per call
    tiny = jax.jit(lambda a: a + 1.0)
    overhead = {}
    for d in devices:
        tx = jax.device_put(jnp.float32(0.0), d)
        y = tiny(tx)
        jax.block_until_ready(y)
        overhead[d] = y
    return overhead


def epoch_loop(steps, x):
    results = []
    for _ in range(steps):
        fn = jax.jit(lambda a: a * 2.0)  # rebuilt (and recompiled) per step
        results.append(fn(x))
    return results
