"""G008 seed: a bare wall-clock delta recorded as a metric.

The pre-graftscope engine idiom: an epoch wall measured with a raw
``perf_counter()`` pair lands directly in the recorder's series — so the
trace cannot attribute it (it lives outside every span) and ``graftscope
diff`` can never explain a regression in it. The sanctioned forms measure
under a span (the wall then IS a trace event) or aggregate through
TimeKeeper/HostOverheadMeter.
"""

import time


def run_epoch(recorder, dispatch, epoch):
    t0 = time.perf_counter()
    dispatch()
    wall = time.perf_counter() - t0
    recorder.record_epoch(epoch=epoch, train_time=wall)
    return wall


def run_epoch_meta(recorder, dispatch):
    t0 = time.perf_counter()
    dispatch()
    overhead = min(0.5, time.perf_counter() - t0)
    recorder.meta["dispatch_overhead_s"] = round(overhead, 6)
    return overhead
