"""G003 seeds: a raw batch-size value becomes a compiled shape.

Every value off the sanctioned shape discipline is a fresh XLA compile inside
the epoch — the recompile-churn contract tests/test_compile_discipline.py
guards end-to-end. Two shapes of the bug:

* vision: a batch width that never passed the bucket ladder
  (snap_to_bucket/quantize_batches);
* LM/SP: a raw per-worker column split that never passed the
  batchify/bptt_windows/pad_bsz channel (the column-count discipline).
"""

import jax
import numpy as np

step = jax.jit(lambda x: x.sum())


def train_epoch(cfg, n_left):
    b = cfg.batch_size - (n_left % cfg.batch_size)  # not bucket-snapped
    x = np.zeros((b, 32, 32, 3), dtype=np.float32)
    return step(x)


def lm_epoch(cfg, batch_sizes, rank):
    # raw solver split used as a column count: off the batchify/pad_bsz
    # channel, so every rebalance compiles a fresh column width
    cols = batch_sizes[rank]
    x = np.zeros((cols, 35), dtype=np.int32)
    return step(x)
