"""G003 seed: a raw batch-size value becomes a compiled shape.

Every value of ``b`` off the bucket ladder is a fresh XLA compile inside the
epoch — the recompile-churn contract tests/test_compile_discipline.py guards
end-to-end."""

import jax
import numpy as np

step = jax.jit(lambda x: x.sum())


def train_epoch(cfg, n_left):
    b = cfg.batch_size - (n_left % cfg.batch_size)  # not bucket-snapped
    x = np.zeros((b, 32, 32, 3), dtype=np.float32)
    return step(x)
