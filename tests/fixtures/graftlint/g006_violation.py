"""G006 seed: a host→device transfer issued EVERY step of a hot loop that
also dispatches a compiled executable — the transfer serializes with the
dispatch queue instead of overlapping compute (the pattern the elastic
superstep/transfer-pipeline rework removed; stage the window once instead).
"""

import jax

step = jax.jit(lambda p, x: (p * x).sum())


def train_epoch(params, batches, dev):
    total = 0.0
    for b in batches:
        x = jax.device_put(b, dev)  # per-step put in the dispatch loop
        total += step(params, x)
    return total
