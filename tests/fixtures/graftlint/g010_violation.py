"""Seeded G010 violations: blocking device-side calls in a retry/recovery
scope with no ``heartbeat()`` coverage and no retry/timeout wrapper.

Recovery scopes run exactly when the fleet is misbehaving — a blocking PJRT
call there can hang in C++ against a dead runtime, and without a heartbeat
the stall watchdog reads the recovery itself as the hang.
"""

import jax

from dynamic_load_balance_distributeddnn_tpu.runtime.health import (
    retry_transient,
)


class MiniEngine:
    def __init__(self, steps, state):
        self.steps = steps
        self.state = state

    def _recover_world(self, survivors, dev):
        # G010: device_put + block_until_ready in a recovery scope, no
        # heartbeat anywhere in the function
        placed = jax.device_put(self.state, dev)
        jax.block_until_ready(placed)
        return survivors

    def _readmit_worker(self, lowered):
        # G010: a blocking XLA backend compile on the readmission edge
        return lowered.compile()

    def _reshard_guarded(self, survivors, dev):
        # quiet: the blocking edge rides retry_transient's tick/backoff
        return retry_transient(lambda: jax.device_put(self.state, dev))
