"""G004 seed: host coercion / Python control flow on traced values."""

import jax
import numpy as np


@jax.jit
def bad_step(params, x):
    if float(x.mean()) > 0:  # branch resolved once at trace time
        x = x - np.asarray(x).mean()  # tracer -> numpy: breaks under jit
    return (params * x).sum()
