"""Near-miss GOOD patterns: everything here is the sanctioned form of a
pattern some G00x rule flags — the linter must stay quiet on all of it."""

import time

import jax
import jax.numpy as jnp
import numpy as np

# G001 good: module-scope construction, compiled once per process
step = jax.jit(lambda p, b: (p * b).sum())
tiny_probe = jax.jit(lambda a: a + 1.0)


class Library:
    def __init__(self):
        # G001 good: __init__ is a setup scope
        self.update = jax.jit(lambda s, g: s - 0.1 * g, donate_argnums=(0,))


def make_ring(mesh_size):
    # G001 good: builder idiom — callers cache the result
    return jax.jit(lambda t: t * mesh_size)


def timed_epoch(params, batch):
    # G002 good: the dispatched result is blocked on inside the window
    t0 = time.perf_counter()
    loss = step(params, batch)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return loss, dt


def train_epoch(cfg, plan):
    # G003 good: the width flows through the bucket quantizer
    b = (cfg.batch_size // cfg.bucket) * cfg.bucket
    x = np.zeros((b, 8), dtype=np.float32)
    return step(jnp.float32(1.0), x)


@jax.jit
def good_step(params, x):
    # G004 good: static metadata reads and lax control flow
    scale = 1.0 / max(x.shape[0], 1)
    return jax.lax.cond(
        jnp.all(x > 0), lambda v: v.sum() * scale, lambda v: v.sum(), (params * x)
    )


def apply_update(lib, state, grads):
    # G005 good: the donated buffer is rebound from the call's result
    state = lib.update(state, grads)
    return state


def lm_epoch(cfg, stream, bptt_windows, batchify):
    # G003 good (LM/SP discipline): the column count flows through the
    # batchify/bptt_windows channel before any compiled shape sees it
    data = batchify(stream, cfg.batch_size)
    xs, ys, ms = bptt_windows(data, cfg.bptt)
    return step(jnp.float32(1.0), xs[0])


def windowed_epoch(params, windows, dev):
    # G006 good: the window stages ONCE in its own loop; the step loop only
    # dispatches (the transfer-pipeline idiom)
    total = 0.0
    for win in windows:
        staged = []
        for arr in win:
            staged.append(jax.device_put(arr, dev))
        for x in staged:
            total += step(params, x)
    return total


def warm_shapes(params, ladder, dev):
    # G006 good: warm/setup scopes pre-compile the ladder — a put per rung
    # alongside the dispatch is the point
    for b in ladder:
        x = jax.device_put(np.zeros((b, 8), np.float32), dev)
        step(params, x)
