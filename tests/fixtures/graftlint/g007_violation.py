"""Seeded G007 violations.

Pattern A: a warm scope that compiles by EXECUTING dummy steps — dispatch
plus block_until_ready in a loop, results discarded — the serial
execute-to-compile warm wall the AOT compile service replaces.

Pattern B: a blocking ``lowered.compile()`` inside a wall-clock window —
the wall measures the XLA compiler, not the program.
"""

import time

import jax
import numpy as np

step = jax.jit(lambda p, x: (p * x).sum())


def warm_ladder(params, ladder, dev):
    for b in ladder:
        x = jax.device_put(np.zeros((b, 8), np.float32), dev)
        out = step(params, x)  # G007: execute-to-compile
        jax.block_until_ready(out)


def timed_epoch(params, x):
    t0 = time.perf_counter()
    lowered = step.lower(params, x)
    lowered.compile()  # G007: the wall times the compiler
    loss = step(params, x)
    jax.block_until_ready(loss)
    return time.perf_counter() - t0
