"""Seeded G009 violations: hot-path dispatch/compile bypassing the AOT
service registry.

Pattern A: a dispatch hot scope calling a StepLibrary executable (or a
jit-bound module callable) directly — the warm/speculative compiles sitting
in the ``AOTCompileService`` registry are never consulted, so a shape
already compiled in the background recompiles lazily in the foreground.

Pattern B: a direct ``fn.lower(args)`` / ``lowered.compile()`` outside the
service — the executable never registers for reuse and the compile is
invisible to the service's dedup/stats.
"""

import jax

from dynamic_load_balance_distributeddnn_tpu.runtime.compiler import (
    AOTCompileService,
)

hot_step = jax.jit(lambda p, x: (p * x).sum())


class MiniEngine:
    def __init__(self, steps):
        self.steps = steps
        self._aot = AOTCompileService()

    def _dispatch_combine_steps(self, state, stacked):
        # G009: direct StepLibrary dispatch in the steady-state hot loop
        return self.steps.combine_update(state, stacked)

    def run_epoch(self, params, x):
        # G009: jit-bound module callable dispatched around the registry
        return hot_step(params, x)

    def _stage_plan(self, params, x):
        # G009 x2: lowers + compiles outside the service — unregistered
        lowered = hot_step.lower(params, x)
        return lowered.compile()
