"""Seeded G010 violations, rendezvous flavor (ISSUE 14): blocking
coordination-service edges in a re-rendezvous scope with no heartbeat/tick
coverage and no retry/timeout armor.

The rendezvous runs exactly while the fleet is broken — an unarmored
``jax.distributed.initialize`` (or client connect / barrier wait) against a
wedged peer hangs the recovery itself, and the stall watchdog then reads the
recovery as the hang it exists to abort.
"""

import jax

from dynamic_load_balance_distributeddnn_tpu.runtime.health import (
    retry_transient,
)


class MiniRendezvous:
    def __init__(self, address, client):
        self.address = address
        self.client = client

    def _rendezvous_reinit(self, num, rank):
        # G010: a blocking world bring-up in a rendezvous scope — no tick,
        # no retry armor; a dead coordinator hangs this forever
        jax.distributed.initialize(
            coordinator_address=self.address,
            num_processes=num,
            process_id=rank,
        )

    def _establish_connect(self):
        # G010: bare client connect in an establish scope
        self.client.connect()

    def _agree_barrier(self, key):
        # G010: a coordination-service barrier wait a dead peer never answers
        self.client.wait_at_barrier(key, timeout_in_ms=10_000)

    def _rendezvous_guarded(self, num, rank, tick):
        # quiet: armored by retry_transient (bounded backoff + tick)
        retry_transient(
            lambda: jax.distributed.initialize(
                coordinator_address=self.address,
                num_processes=num,
                process_id=rank,
            ),
            tick=tick,
        )
