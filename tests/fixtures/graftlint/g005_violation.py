"""G005 seed: reading a buffer after donating it.

On TPU the donated input's storage is reused for the output; the later read
returns garbage or raises a deleted-buffer error."""

import jax
import jax.numpy as jnp

update = jax.jit(lambda state, grads: state - 0.1 * grads, donate_argnums=(0,))


def apply_update(state, grads):
    new_state = update(state, grads)  # `state`'s buffer is donated here
    drift = jnp.abs(state - new_state).max()  # reads the donated buffer
    return new_state, drift
