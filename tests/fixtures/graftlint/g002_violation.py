"""G002 seed: wall-clock window over an async dispatch with no sync.

The `block_until_ready`-over-tunnel gotcha (VERDICT.md round 5): the jit call
returns as soon as the work is enqueued, so the wall measures dispatch
latency, not compute."""

import time

import jax

step = jax.jit(lambda p, b: (p * b).sum())


def timed_epoch(params, batches):
    t0 = time.time()
    loss = None
    for b in batches:
        loss = step(params, b)  # async: returns before the device runs
    dt = time.time() - t0  # measures enqueue time, not the epoch
    return loss, dt
