"""G014 seeds: axis-TUPLE VARIABLES in collective axis args (PR-13
satellite). The two-level combine spells its collectives over a variable
bound to an axis tuple; before the local-bind resolver those spellings
erred quiet, so a typo'd member axis (or a stale string variable) was
invisible.

Shape 1: ``combine`` psums over ``axes = (HOST, "devicee")`` — the tuple
resolves through the local bind and the constant, exposing the member typo
no mesh defines.

Shape 2: ``index`` reads ``axis_index(ax)`` where ``ax = "dat"`` — a
string VARIABLE naming an axis no mesh construction defines.
"""

import jax
import numpy as np
from jax.sharding import Mesh

HOST = "host"
DEVICE = "device"


def make_mesh(devices):
    return Mesh(np.array(devices).reshape(2, -1), (HOST, DEVICE))


def combine(tree):
    axes = (HOST, "devicee")  # typo'd member, hidden behind a variable
    return jax.lax.psum(tree, axes)


def index(x):
    ax = "dat"  # no mesh defines 'dat'
    return jax.lax.axis_index(ax) + x
