"""Seeded G019 violation (pool-allocator shape, ISSUE 18): the device-pool
allocator re-partitions its ordinal→tenant map while the request-staging
thread it spawned at construction is still live — no lock around the
topology write, no quiesce step before it. A tenant staged against the old
partition keeps dispatching onto ordinals that now belong to someone else.
(The in-tree ``DevicePool`` gates every ``_mesh`` write on
``_quiesce_pool()`` — topology writes are legal only between windows.)
"""

import threading


def empty_mesh(n):
    return {d: None for d in range(n)}


class Pool:
    def __init__(self, n):
        self._lock = threading.Lock()
        self._requests = []
        self._mesh = empty_mesh(n)
        self._server = threading.Thread(target=self._serve, daemon=True)
        self._server.start()

    def _serve(self):
        while True:
            with self._lock:
                if self._requests:
                    self._requests.pop()

    def request(self, job):
        with self._lock:
            self._requests.append(job)

    def reallocate(self, n):
        self._mesh = empty_mesh(n)  # staging thread still running
