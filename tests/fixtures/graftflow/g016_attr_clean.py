"""G016 negatives for the self-attr / container channels: the SAME store-
on-self and append-into-container shapes, but the values pass the
pad/quantize discipline BEFORE they are stored — the ladder widths a
collective can legally see."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh


def make_mesh(devices):
    return Mesh(np.array(devices), ("data",))


def integer_batch_split(shares, global_batch):
    return np.maximum((shares * global_batch).astype(np.int64), 1)


def quantize_batches(batches, bucket, global_batch):
    return np.maximum(batches // bucket, 1) * bucket


class Controller:
    def __init__(self):
        self._sizes = None
        self._cols = []

    def plan(self, shares, global_batch, bucket):
        raw = integer_batch_split(shares, global_batch)
        self._sizes = quantize_batches(raw, bucket, global_batch)  # snapped

    def dispatch(self, parts, pad_to):
        shards = [np.pad(p, (0, pad_to - len(p))) for p in parts]  # padded
        stacked = jnp.stack(shards)
        return jax.lax.all_gather(stacked, "data")

    def collect(self, shares, global_batch, bucket):
        raw = integer_batch_split(shares, global_batch)
        self._cols.append(quantize_batches(raw, bucket, global_batch))

    def flush(self):
        return jnp.stack(self._cols)
