"""G016 seeds: non-uniform shard arithmetic, two shapes.

DBS plans are UNEQUAL by design — the solver's per-worker batch sizes
differ until the pad/quantize discipline snaps them to the bucket ladder.

Shape 1 (local): ``pack`` slices per-worker shards to their raw plan
widths, then stacks and all_gathers them — XLA collectives need every
participant to contribute the same shape, so the unequal shards either
fail to trace or silently truncate.

Shape 2 (interprocedural): ``epoch`` hands the raw
``integer_batch_split`` output to ``gather_all``, whose body feeds its
parameter into a fixed-shape collective — the taint and the sink live in
different functions.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh


def make_mesh(devices):
    return Mesh(np.array(devices), ("data",))


def integer_batch_split(shares, global_batch):
    return np.maximum((shares * global_batch).astype(np.int64), 1)


def pack(parts, batch_sizes):
    shards = [p[:b] for p, b in zip(parts, batch_sizes)]  # raw plan widths
    stacked = jnp.stack(shards)
    return jax.lax.all_gather(stacked, "data")


def gather_all(vec):
    return jax.lax.all_gather(vec, "data")  # fixed-shape sink


def epoch(shares, global_batch):
    batches = integer_batch_split(shares, global_batch)
    return gather_all(batches)  # unequal widths cross the call boundary
