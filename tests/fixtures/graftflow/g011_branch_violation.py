"""G011 branch-sensitivity seed (positive twin of g011_branch_clean.py):
the alias and the donation share the SAME If arm, so the path through the
arm really does read a donated buffer — branch-aware alias groups must
still fire here."""

import jax
import jax.numpy as jnp

step = jax.jit(lambda s, g: s - g, donate_argnums=(0,))


def window(state, grads, fastpath):
    if fastpath:
        snap = state  # alias in the SAME arm as the donation
        state = step(state, grads)
        return state, jnp.sum(snap)  # snap still points at the donated buffer
    return state, jnp.zeros(())
