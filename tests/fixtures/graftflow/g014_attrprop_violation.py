"""Seeded G014, attribute-valued axis spellings (ISSUE 14 satellite — the
recorded PR-13 residual gap): a collective whose axis argument is a live
``self.<attr>`` property.

Two bug classes:

* ``_axis_arg`` returns an OPAQUE computed value — no resolution channel
  grounds it, which used to err quiet; now it is an explicit "unresolved
  axis expression" finding.
* ``_typo_axis`` RESOLVES (a literal-returning property) to an axis no mesh
  in the program defines — the ordinary unknown-axis finding, reachable
  through the new property channel.
* ``_masked_axis`` reads ``axis_names`` for an UNRELATED value and returns
  an opaque attribute — the consistency-by-construction fallback must key
  on the RETURNED value's derivation, not on any read in the body, or this
  errs quiet again.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh


def build_mesh(devices):
    return Mesh(np.array(devices), ("data",))


class OpaqueSteps:
    def __init__(self, mesh):
        self.mesh = mesh

    @property
    def _axis_arg(self):
        # opaque: a computed string no static channel can ground
        return "".join(["da", "ta"])

    @property
    def _typo_axis(self):
        # resolves to a literal — but "dat" is defined by no mesh
        return "dat"

    @property
    def _masked_axis(self):
        # the axis_names read feeds an unrelated value; the RETURN is
        # opaque — must still be an unresolved-axis-expression finding
        n = len(self.mesh.axis_names)
        self._n_axes = n
        return self._dynamic_expr

    def combine(self, grads):
        # G014: unresolved axis expression (the property is opaque)
        return jax.lax.psum(grads, self._axis_arg)

    def combine_typo(self, grads):
        # G014: resolved through the property to an axis no mesh defines
        return jax.lax.psum(grads, self._typo_axis)

    def combine_masked(self, grads):
        # G014: the unrelated axis_names read must not silence this
        return jax.lax.psum(grads, self._masked_axis)


def run(devices, grads):
    mesh = build_mesh(devices)
    steps = OpaqueSteps(mesh)
    steps._dynamic_expr = "".join(["da", "ta"])
    return (
        steps.combine(jnp.asarray(grads)),
        steps.combine_typo(jnp.asarray(grads)),
        steps.combine_masked(jnp.asarray(grads)),
    )
