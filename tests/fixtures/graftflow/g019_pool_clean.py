"""Clean twin of g019_pool_violation.py: the same re-partition, but the
allocator drains its request-staging thread first (``_quiesce_allocator``
joins it, bounded) and rebinds the map under the lock — the window-boundary
discipline the in-tree ``DevicePool`` enforces with ``_quiesce_pool``.
G019 accepts a preceding ``*quiesce*``/``*drain*`` call, a lock held at the
write, or a lock held by every caller; this twin satisfies the first two.
"""

import threading


def empty_mesh(n):
    return {d: None for d in range(n)}


class Pool:
    def __init__(self, n):
        self._lock = threading.Lock()
        self._requests = []
        self._stopped = False
        self._mesh = empty_mesh(n)
        self._server = threading.Thread(target=self._serve, daemon=True)
        self._server.start()

    def _serve(self):
        while True:
            with self._lock:
                if self._stopped:
                    return
                if self._requests:
                    self._requests.pop()

    def request(self, job):
        with self._lock:
            self._requests.append(job)

    def _quiesce_allocator(self):
        with self._lock:
            self._stopped = True
        self._server.join(timeout=5.0)

    def reallocate(self, n):
        self._quiesce_allocator()
        with self._lock:
            self._mesh = empty_mesh(n)
