"""G016 seeds: plan taint through SELF-ATTRS and CONTAINER ELEMENTS.

The window-cadence controller stores plan-derived sizes on ``self`` and
packs per-worker columns into lists before dispatch — without these two
channels the new code's riskiest sites are invisible to the lint gate.

Shape 1 (self-attr): ``plan`` stores the raw ``integer_batch_split``
output on ``self._sizes``; ``dispatch`` — a different method — slices
per-worker shards to those widths and stacks them into a fixed-shape
collective.

Shape 2 (container element): ``collect`` appends the raw batch vector
into ``self._cols`` (a container MUTATION, not a rebind); ``flush``
device-stacks the container.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh


def make_mesh(devices):
    return Mesh(np.array(devices), ("data",))


def integer_batch_split(shares, global_batch):
    return np.maximum((shares * global_batch).astype(np.int64), 1)


class Controller:
    def __init__(self):
        self._sizes = None
        self._cols = []

    def plan(self, shares, global_batch):
        self._sizes = integer_batch_split(shares, global_batch)

    def dispatch(self, parts):
        shards = [p[:b] for p, b in zip(parts, self._sizes)]  # raw widths
        stacked = jnp.stack(shards)
        return jax.lax.all_gather(stacked, "data")

    def collect(self, shares, global_batch):
        batches = integer_batch_split(shares, global_batch)
        self._cols.append(batches)  # element mutation carries the taint

    def flush(self):
        return jnp.stack(self._cols)  # device concat of unequal columns
