"""G015 seeds: sharding-spec flow, the two motivating incidents.

Shape 1 (cross-function stale spec — the PR-6 restore-onto-old-mesh crash,
one function boundary deeper than G013 sees): ``resume`` obtains the state
sharding THROUGH ``_sharding_for_state`` (so no mesh identifier appears in
the bind and G013's local-capture rule is blind), then the elastic branch
re-shards, then ``device_put`` places with the pre-reshard spec —
replicated over the ORIGINAL device set, mixed-device crash at the first
combine.

Shape 2 (lowering-spec vs dispatch-placement mismatch — the fused-AOT seed
incident): ``_submit_aot`` lowers the executable from specs registered
replicated (``P()``), but ``_dispatch`` commits the operand under
``P("data")`` — a sharding the executable was never lowered for, so
dispatch either recompiles silently or rejects the operand.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class Engine:
    def __init__(self, devices):
        self.mesh = Mesh(np.array(devices), ("data",))
        self._aot = object()

    def _sharding_for_state(self):
        return NamedSharding(self.mesh, P())

    def _reshard_world(self, active):
        self.mesh = Mesh(np.array(active), ("data",))

    def resume(self, ckpt, active):
        sh = self._sharding_for_state()  # captured THROUGH the helper
        if ckpt.active != active:
            self._reshard_world(active)
        return jax.device_put(ckpt.state, sh)  # STALE pre-reshard spec

    def _submit_aot(self, state):
        seed_t = jax.ShapeDtypeStruct(
            (), jnp.int32, sharding=NamedSharding(self.mesh, P())
        )
        self._aot.submit(("fused", 0), state, (seed_t,))

    def _dispatch(self, epoch):
        seed = jax.device_put(
            jnp.int32(epoch), NamedSharding(self.mesh, P("data"))
        )  # lowered under P(), dispatched under P("data")
        return seed


def make_mesh(devices):
    return Mesh(np.array(devices), ("data",))
