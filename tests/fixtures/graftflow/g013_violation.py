"""G013 seed: the pre-PR-6 restore-onto-old-mesh crash, minimized.

Shape 1 (local): ``resume`` builds a NamedSharding from ``self.mesh``
BEFORE the elastic path can call ``_reshard_world``, then places the
restored state with the stale capture — replicated over the full ORIGINAL
device set, mixed-device crash at the first combine.

Shape 2 (class invariant): ``_build_cache`` stores a mesh-derived sharding
in an attribute that no re-shard path ever rebinds.
"""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


class Engine:
    def __init__(self, mesh, active):
        self.mesh = mesh
        self.active = list(active)

    def _reshard_world(self, active):
        self.active = list(active)
        self.mesh = _data_mesh(self.active)

    def resume(self, ckpt):
        sharding = NamedSharding(self.mesh, P("data"))  # pre-reshard capture
        state = _load_state(ckpt)
        if ckpt.active != self.active:
            self._reshard_world(ckpt.active)
        return jax.device_put(state, sharding)  # STALE mesh placement

    def _build_cache(self):
        # mesh-derived attribute: _reshard_world never rebinds it
        self._repl_sharding = NamedSharding(self.mesh, P())

    def place(self, x):
        return jax.device_put(x, self._repl_sharding)


def _data_mesh(active):
    return object()


def _load_state(ckpt):
    return object()
