"""G012 negatives: the disciplined twin of the drain-race fixture.

Every cross-thread access of ``_pool``/``_stopped`` holds ``self._lock`` —
including interprocedurally: ``_ensure_pool_locked`` itself takes no lock,
but its only call sites hold it, so the callgraph's lock environment proves
its writes guarded (the compiler.py ``_ensure_pool_locked`` idiom).
"""

import threading


class CompileService:
    def __init__(self):
        self._lock = threading.Lock()
        self._pool = None
        self._stopped = False
        self._feeder_thread = threading.Thread(target=self._feeder, daemon=True)
        self._feeder_thread.start()

    def _ensure_pool_locked(self):
        # callers hold self._lock (lock-env propagation, not lexical)
        if self._pool is None:
            self._pool = _spawn_pool()
        return self._pool

    def _feeder(self):
        while True:
            with self._lock:
                if self._stopped:
                    return
                pool = self._ensure_pool_locked()
            pool.feed()

    def close(self):
        with self._lock:
            self._stopped = True
            self._pool = None


def _spawn_pool():
    return object()
