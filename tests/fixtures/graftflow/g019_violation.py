"""Seeded G019 violation (quiesce discipline): the engine rebuilds its
device mesh while the staging thread it spawned at construction is still
live — no lock around the write, no drain/quiesce step before it. The
"synchronized by program order" argument the in-tree ``_reshard_world``
used to make is exactly what this shape breaks: a staging thread that
reads the topology mid-rebuild stages window buffers against a mesh that
no longer exists. (The in-tree fix is ``_quiesce_pipeline()`` at the top
of the rebuild.)
"""

import threading


def build_mesh(devices):
    return tuple(devices)


class Engine:
    def __init__(self, devices):
        self._lock = threading.Lock()
        self._jobs = []
        self.mesh = build_mesh(devices)
        self._stager = threading.Thread(target=self._stage, daemon=True)
        self._stager.start()

    def _stage(self):
        while True:
            with self._lock:
                if self._jobs:
                    self._jobs.pop()

    def submit(self, job):
        with self._lock:
            self._jobs.append(job)

    def rebuild(self, devices):
        self.mesh = build_mesh(devices)  # staging thread still running
