"""G013 negatives: the three sanctioned stale-mesh disciplines.

* rebuild the sharding from ``self.mesh`` AFTER the possible re-shard
* generation-key mesh-derived caches with ``_aot_gen`` (stale keys miss)
* have the re-shard path itself rebind the derived attribute
"""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


class Engine:
    def __init__(self, mesh, active):
        self.mesh = mesh
        self.active = list(active)
        self._aot_gen = 0
        self._view_specs = {}

    def _reshard_world(self, active):
        self.active = list(active)
        self.mesh = _data_mesh(self.active)
        self._aot_gen += 1
        self._repl_sharding = NamedSharding(self.mesh, P())  # rebinds

    def resume(self, ckpt):
        state = _load_state(ckpt)
        if ckpt.active != self.active:
            self._reshard_world(ckpt.active)
        sharding = NamedSharding(self.mesh, P("data"))  # post-reshard: fresh
        return jax.device_put(state, sharding)

    def _build_cache(self, key):
        # generation-keyed: entries from an old mesh can never resolve
        self._view_specs[key] = (self._aot_gen, NamedSharding(self.mesh, P()))

    def _build_repl(self):
        self._repl_sharding = NamedSharding(self.mesh, P())  # reshard rebinds

    def place(self, x):
        return jax.device_put(x, self._repl_sharding)


def _data_mesh(active):
    return object()


def _load_state(ckpt):
    return object()
