"""G015 negatives for the per-executable-key matching: a dispatch whose
placement matches ITS key's registered spec is clean, and a dispatch with
no extractable key literal falls back to the class-wide union (strictly
the pre-satellite behavior — precision only ever increases).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class Engine:
    def __init__(self, devices):
        self.mesh = Mesh(np.array(devices), ("data",))
        self._aot = object()

    def _submit_fused(self, state):
        seed_t = jax.ShapeDtypeStruct(
            (), jnp.int32, sharding=NamedSharding(self.mesh, P())
        )
        self._aot.submit(("fused", 0), state, (seed_t,))

    def _submit_stacked(self, grads):
        g_t = jax.ShapeDtypeStruct(
            (4, 8), jnp.float32, sharding=NamedSharding(self.mesh, P("data"))
        )
        self._aot.submit(("stacked", 0), grads, (g_t,))

    def _dispatch_fused(self, epoch):
        fn = self._aot.get(("fused", 0))
        seed = jax.device_put(
            jnp.int32(epoch), NamedSharding(self.mesh, P())
        )  # matches the "fused" key's registered lowering
        return fn, seed

    def _dispatch_any(self, key, grads):
        fn = self._aot.get(key)  # opaque key: class-wide union applies
        stacked = jax.device_put(
            grads, NamedSharding(self.mesh, P("data"))
        )  # registered by the "stacked" scope
        return fn, stacked


def make_mesh(devices):
    return Mesh(np.array(devices), ("data",))
