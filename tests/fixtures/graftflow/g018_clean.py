"""Clean twin of g018_violation.py: the same recovery surface in automaton
order (flush -> agree -> retire -> establish -> reshard -> restore), plus
two shapes the rule must tolerate: a phase call wrapped in a retry lambda
(the engine's ``retry_transient(lambda: self._reshard_world(...))``
idiom) and an if/else whose arms each run a LOWER phase than the other
arm's — exclusive branches are separate recovery paths, not inversions.
"""


def retry_transient(fn):
    return fn()


class Recovery:
    def flush_checkpoints(self):
        pass

    def agree(self, survivors):
        return list(survivors)

    def retire_runtime(self):
        pass

    def establish(self, survivors):
        pass

    def _reshard_world(self, survivors):
        pass

    def _state_from_host(self, host_state):
        return host_state

    def recover(self, survivors, host_state, fast=False):
        self.flush_checkpoints()
        roster = self.agree(survivors)
        if fast:
            self.establish(roster)
        else:
            self.retire_runtime()  # other arm of the same If: no inversion
            self.establish(roster)
        retry_transient(lambda: self._reshard_world(roster))
        return self._state_from_host(host_state)
