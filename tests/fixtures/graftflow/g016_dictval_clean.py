"""G016 negatives for the dict-VALUE iteration channel: the SAME staging
dict and ``.values()`` / ``.items()`` loops, but every stored column passed
the pad/quantize discipline first — ladder widths a collective can legally
see."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh


def make_mesh(devices):
    return Mesh(np.array(devices), ("data",))


def integer_batch_split(shares, global_batch):
    return np.maximum((shares * global_batch).astype(np.int64), 1)


def quantize_batches(batches, bucket, global_batch):
    return np.maximum(batches // bucket, 1) * bucket


def stack_values(parts, shares, global_batch, bucket, pad_to):
    batches = quantize_batches(
        integer_batch_split(shares, global_batch), bucket, global_batch
    )
    cols = {}
    for r in range(len(parts)):
        cols[r] = np.pad(parts[r], (0, pad_to - len(parts[r])))  # padded
    out = []
    for v in cols.values():
        out.append(v)
    return jnp.stack(out), batches


def gather_items(parts, pad_to):
    cols = {}
    for r in range(len(parts)):
        cols[r] = np.pad(parts[r], (0, pad_to - len(parts[r])))
    gathered = []
    for r, v in cols.items():
        gathered.append(jax.lax.all_gather(v, "data"))
    return gathered
