"""G012 seed: the pre-PR-5 compile-service drain race, minimized.

The shipped shape: ``close()`` mutates the worker-pool handle and the
shutdown flag on the main thread with NO lock, while the feeder thread
reads the flag and re-creates the pool through ``_ensure_pool`` — a pending
job racing the drain respawns a pool that close() then leaks. Every access
of ``_pool``/``_stopped`` crosses threads; none holds a common lock.
"""

import threading


class CompileService:
    def __init__(self):
        self._lock = threading.Lock()
        self._pool = None
        self._stopped = False
        self._feeder_thread = threading.Thread(target=self._feeder, daemon=True)
        self._feeder_thread.start()

    def _ensure_pool(self):
        if self._pool is None:  # feeder thread: unguarded check...
            self._pool = _spawn_pool()  # ...then unguarded respawn
        return self._pool

    def _feeder(self):
        while not self._stopped:  # unguarded cross-thread flag read
            pool = self._ensure_pool()
            pool.feed()

    def close(self):
        self._stopped = True  # main thread: unguarded flag write
        self._pool = None  # races the feeder's respawn -> leaked pool


def _spawn_pool():
    return object()
