"""G014 negatives for the axis-tuple-variable resolver: collectives over
variables bound to tuples/strings of DEFINED axes (directly or through
module constants) stay quiet, and an opaque rebind (attribute-valued)
keeps the errs-quiet contract."""

import jax
import numpy as np
from jax.sharding import Mesh

HOST = "host"
DEVICE = "device"


def make_mesh(devices):
    return Mesh(np.array(devices).reshape(2, -1), (HOST, DEVICE))


def combine(tree):
    axes = (HOST, DEVICE)  # both defined, resolved through constants
    return jax.lax.psum(tree, axes)


def in_host(x):
    ax = DEVICE  # string variable of a defined axis (constant alias bind)
    return jax.lax.psum(x, (ax,))


def opaque(self_like, x):
    axes = self_like.batch_axes  # attribute-valued: stays unresolved/quiet
    return jax.lax.psum(x, axes)
