"""G014 seeds: N-tuple collective axes (ISSUE 17). The N-level tree combine
spells its collectives over 3- and 4-member axis tuples; each shape below
hides one member no mesh defines — exactly the spellings the generalized
``tree_allreduce`` ships, so the resolver must walk tuples of ANY length,
not just the two-level (host, device) pair.

Shape 1: ``combine`` psums over the full 4-tuple with a typo'd middle
member ("rak").

Shape 2: ``reduce_up`` scatters over a 3-member sub-tuple bound to a
variable that carries a stale axis name from the two-level era ("hosts").

Shape 3: ``index`` reads ``axis_index`` of a level the tree was declared
without.
"""

import jax
import numpy as np
from jax.sharding import Mesh

DCN = "dcn"
RACK = "rack"
HOST = "host"
DEVICE = "device"


def make_mesh(devices):
    return Mesh(
        np.array(devices).reshape(2, 2, 2, -1), (DCN, RACK, HOST, DEVICE)
    )


def combine(tree):
    return jax.lax.psum(tree, (DCN, "rak", HOST, DEVICE))  # typo'd member


def reduce_up(x):
    inner = ("hosts", HOST, DEVICE)  # stale two-level-era axis name
    return jax.lax.psum_scatter(x, inner, scatter_dimension=0, tiled=True)


def index(x):
    return jax.lax.axis_index("pod") + x  # level never declared
