"""G014 seed: the axis-param override channel must EXTEND the universe, not
disarm the rule — the call site defines axis "model", and the collective
typos it as "modle", which no mesh (default "data", override "model")
defines.
"""

import jax
import numpy as np
from jax.sharding import Mesh


def build(devices, axis="data"):
    return Mesh(np.array(devices), (axis,))


def combine(tree, devices):
    mesh = build(devices, axis="model")
    with mesh:
        return jax.lax.psum(tree, "modle")  # typo: not 'data', not 'model'
