"""G011 seed: the pre-PR-6 donated-restore use-after-free, minimized.

Shape 1 (the shipped bug): ``restore_checkpoint`` returns
``device_put(restored)`` — on the CPU backend a ZERO-COPY alias of host
memory the checkpoint machinery owns — and the caller donates that value to
a hot-path dispatch. Donation frees storage the external owner still holds:
segfault in ``addressable_shards`` a few steps later, heap-layout dependent.

Shape 2: donation happens inside a callee (``apply``), the read in the
caller — invisible to single-file G005.

Shape 3: an alias (``snap = state``) taken before a donate-and-rebind; the
rebound name is fresh but the alias still points at the donated buffer.
"""

import jax
import jax.numpy as jnp

update = jax.jit(lambda state, grads: state - 0.1 * grads, donate_argnums=(0,))


def restore_checkpoint(mgr, step, sharding):
    restored = mgr.restore(step)  # orbax owns these host buffers
    return jax.device_put(restored, sharding)  # zero-copy alias on CPU


def resume_and_step(mgr, step, sharding, grads):
    state = restore_checkpoint(mgr, step, sharding)
    return update(state, grads)  # donates the externally-aliased buffer


def apply(state, grads):
    return update(state, grads)  # donates its param 0


def outer(state, grads):
    new = apply(state, grads)  # `state` dies in the callee
    drift = jnp.abs(state - new).max()  # read of the donated buffer
    return new, drift


def window(state, grads_seq):
    snap = state  # alias of the original buffer
    for g in grads_seq:
        state = update(state, g)  # donate-and-rebind: `state` is fresh...
    return state, jnp.sum(snap)  # ...but `snap` still points at round 0
