"""Clean twin of g019_violation.py: the same rebuild, but the concurrent
consumer is drained first — ``_drain_staging`` joins the staging thread
(bounded) before the mesh write, turning the program-order argument into
an enforced quiesce. G019 accepts a preceding ``*quiesce*``/``*drain*``
call, a lock held at the write, or a lock held by every caller.
"""

import threading


def build_mesh(devices):
    return tuple(devices)


class Engine:
    def __init__(self, devices):
        self._lock = threading.Lock()
        self._jobs = []
        self._stopped = False
        self.mesh = build_mesh(devices)
        self._stager = threading.Thread(target=self._stage, daemon=True)
        self._stager.start()

    def _stage(self):
        while True:
            with self._lock:
                if self._stopped:
                    return
                if self._jobs:
                    self._jobs.pop()

    def submit(self, job):
        with self._lock:
            self._jobs.append(job)

    def _drain_staging(self):
        with self._lock:
            self._stopped = True
        self._stager.join(timeout=5.0)

    def rebuild(self, devices):
        self._drain_staging()
        self.mesh = build_mesh(devices)
