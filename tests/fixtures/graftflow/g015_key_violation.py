"""G015 seed: per-EXECUTABLE-KEY registered-lowering matching (PR-12
satellite). The class registers TWO executable families under different
specs: the "fused" key lowers with a replicated ``P()`` seed, the "stacked"
key with a ``P("data")`` grads stack. ``_dispatch_fused`` resolves the
"fused" key but commits its operand under ``P("data")`` — registered for
the OTHER executable only. Class-scoped matching (the pre-satellite
behavior) unioned both registration sets and sanctioned the mismatch.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class Engine:
    def __init__(self, devices):
        self.mesh = Mesh(np.array(devices), ("data",))
        self._aot = object()

    def _submit_fused(self, state):
        seed_t = jax.ShapeDtypeStruct(
            (), jnp.int32, sharding=NamedSharding(self.mesh, P())
        )
        self._aot.submit(("fused", 0), state, (seed_t,))

    def _submit_stacked(self, grads):
        g_t = jax.ShapeDtypeStruct(
            (4, 8), jnp.float32, sharding=NamedSharding(self.mesh, P("data"))
        )
        self._aot.submit(("stacked", 0), grads, (g_t,))

    def _dispatch_fused(self, epoch):
        fn = self._aot.get(("fused", 0))
        seed = jax.device_put(
            jnp.int32(epoch), NamedSharding(self.mesh, P("data"))
        )  # "fused" was lowered under P(), not P("data")
        return fn, seed


def make_mesh(devices):
    return Mesh(np.array(devices), ("data",))
