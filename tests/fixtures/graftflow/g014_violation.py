"""G014 seeds: collective/axis consistency, three shapes.

Shape 1 (axis universe): ``combine`` psums over axis ``"dat"`` — a typo no
mesh construction in the program defines (the only mesh carries ``"data"``).

Shape 2 (shard_map supply vs demand): ``wire`` maps ``body`` over a 1-D
``("data",)`` mesh, but ``body``'s collective requires axis ``"model"`` —
the interprocedural check: the axis use and the mesh live in different
functions.

Shape 3 (elastic size assumption): ``Engine._reshard_world`` rebuilds the
mesh from the RUNTIME survivor fleet, yet ``stage_slow`` sizes a
mesh-sharded vector from ``cfg.world_size`` — after a downsizing re-shard
the static config size no longer matches the mesh axis (the PR-6 class of
bug, size flavor).
"""

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(devices):
    return Mesh(np.array(devices), ("data",))


def combine(tree):
    return jax.lax.psum(tree, "dat")  # no mesh defines 'dat'


def body(x):
    return jax.lax.psum(x, "model")  # demanded axis


def wire(devices):
    mesh = make_mesh(devices)
    return jax.shard_map(body, mesh=mesh, in_specs=None, out_specs=None)


class Engine:
    def __init__(self, cfg, devices):
        self.cfg = cfg
        self.mesh = make_mesh(devices)

    def _reshard_world(self, active):
        self.mesh = make_mesh(active)  # runtime fleet sizes the axis

    def stage_slow(self, faults):
        cfg = self.cfg
        slow = np.zeros(cfg.world_size, np.int32)
        return jax.device_put(slow, stacked_sharding(self.mesh, "data"))


def stacked_sharding(mesh, axis):
    return object()
