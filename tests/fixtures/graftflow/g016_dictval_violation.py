"""G016 seeds: plan taint through dict-VALUE iteration (PR-13 satellite).

The engine's dispatch loops stage per-worker columns in dicts; iterating
``d.values()`` / ``d.items()`` hands each ELEMENT onward — before the
For-iter modeling, the loop target was an opaque fresh binding and the
taint chain broke exactly there.

Shape 1: raw plan widths stored into a dict, re-collected through
``.values()`` and stacked on device.

Shape 2: the ``.items()`` tuple-target spelling, feeding a fixed-shape
collective directly.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh


def make_mesh(devices):
    return Mesh(np.array(devices), ("data",))


def integer_batch_split(shares, global_batch):
    return np.maximum((shares * global_batch).astype(np.int64), 1)


def stack_values(parts, shares, global_batch):
    batches = integer_batch_split(shares, global_batch)
    cols = {}
    for r in range(len(parts)):
        cols[r] = parts[r][: batches[r]]  # raw plan widths
    out = []
    for v in cols.values():  # taint crosses the dict-VALUE iteration
        out.append(v)
    return jnp.stack(out)


def gather_items(parts, shares, global_batch):
    batches = integer_batch_split(shares, global_batch)
    cols = {}
    for r in range(len(parts)):
        cols[r] = parts[r][: batches[r]]
    gathered = []
    for r, v in cols.items():  # the tuple-target spelling
        gathered.append(jax.lax.all_gather(v, "data"))
    return gathered
