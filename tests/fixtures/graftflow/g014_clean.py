"""G014 negatives: the sanctioned axis disciplines.

* collectives name axes a mesh construction actually defines (through the
  module-constant indirection — ``AXIS = "data"`` resolves)
* shard_map's mesh carries every axis the mapped function demands
* the elastic class sizes mesh-shaped values from the RUNTIME
  ``self.world_size`` the re-shard rebinds, not the static config
"""

import jax
import numpy as np
from jax.sharding import Mesh

AXIS = "data"


def make_mesh(devices):
    return Mesh(np.array(devices), (AXIS,))


def combine(tree):
    return jax.lax.psum(tree, AXIS)


def body(x):
    return jax.lax.psum(x, "data")


def wire(devices):
    mesh = make_mesh(devices)
    return jax.shard_map(body, mesh=mesh, in_specs=None, out_specs=None)


class Engine:
    def __init__(self, cfg, devices):
        self.cfg = cfg
        self.mesh = make_mesh(devices)
        self.world_size = cfg.world_size

    def _reshard_world(self, active):
        self.world_size = len(active)
        self.mesh = make_mesh(active)

    def stage_slow(self, faults):
        slow = np.zeros(self.world_size, np.int32)
        return jax.device_put(slow, stacked_sharding(self.mesh, "data"))


def stacked_sharding(mesh, axis):
    return object()
