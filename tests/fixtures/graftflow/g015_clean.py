"""G015 negatives: the sanctioned spec-flow disciplines.

* rebuild the helper-obtained sharding AFTER the possible re-shard
* dispatch placements use the SAME spec identity the AOT lowering
  registered
* generation-keyed placements (``_aot_gen`` in the statement) are
  sanctioned: stale entries can never resolve
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class Engine:
    def __init__(self, devices):
        self.mesh = Mesh(np.array(devices), ("data",))
        self._aot = object()
        self._aot_gen = 0

    def _sharding_for_state(self):
        return NamedSharding(self.mesh, P())

    def _reshard_world(self, active):
        self.mesh = Mesh(np.array(active), ("data",))
        self._aot_gen += 1

    def resume(self, ckpt, active):
        if ckpt.active != active:
            self._reshard_world(active)
        sh = self._sharding_for_state()  # rebuilt AFTER the re-shard
        return jax.device_put(ckpt.state, sh)

    def _submit_aot(self, state):
        seed_t = jax.ShapeDtypeStruct(
            (), jnp.int32, sharding=NamedSharding(self.mesh, P())
        )
        self._aot.submit(("fused", self._aot_gen), state, (seed_t,))

    def _dispatch(self, epoch):
        seed = jax.device_put(
            jnp.int32(epoch), NamedSharding(self.mesh, P())
        )  # matches the registered lowering spec
        return seed


def make_mesh(devices):
    return Mesh(np.array(devices), ("data",))
