"""G014 negative: call-site literal override of a DEFAULTED axis param.

``build`` constructs its mesh through a defaulted ``axis`` parameter; the
call sites override it with ``"model"``. The override must enter the axis
universe AND the bound mesh's value environment (PR-12 satellite — before
it, the universe held only the default ``"data"`` and every collective over
``"model"`` was a false G014), so both the psum over "model" and the
shard_map whose body demands "model" are clean.
"""

import jax
import numpy as np
from jax.sharding import Mesh


def build(devices, axis="data"):
    return Mesh(np.array(devices), (axis,))


def combine(tree, devices):
    mesh = build(devices, axis="model")
    with mesh:
        return jax.lax.psum(tree, "model")


def body(x):
    return jax.lax.psum(x, "model")


def wire(devices):
    mesh = build(devices, axis="model")
    return jax.shard_map(body, mesh=mesh, in_specs=None, out_specs=None)
