"""Seeded G017 violation (protocol-file discipline): a joiner writes its
offer file with a bare ``json.dump`` straight onto the rendezvous path —
a peer whose roster scan races the write reads half a JSON object — and
reads the roster ack with no try/except, so the torn/missing files that
are LEGAL at every point of the protocol (a peer can die mid-write; the
wipe can race a read) crash the reader instead of reading as absent.
Minimized from the incident the atomic ``_write_json``/tolerant
``_read_json`` helpers in runtime/rendezvous.py exist to prevent.
"""

import json
import os


def offer_join(rdzv_dir: str, ident: int) -> None:
    path = os.path.join(rdzv_dir, f"join_p{ident}.json")
    with open(path, "w") as f:
        json.dump({"ident": ident}, f)  # torn in-place protocol write


def read_roster(rdzv_dir: str):
    path = os.path.join(rdzv_dir, "ack_g0.json")
    with open(path) as f:
        return json.load(f)  # unguarded: torn/missing ack raises here
