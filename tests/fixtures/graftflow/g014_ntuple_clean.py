"""G014 negatives for N-tuple collective-axis resolution (ISSUE 17): the
tree combine's collectives run over 3- and 4-member axis tuples — every
member defined by the N-level mesh — as call-site literals, through module
constants, and through sub-tuple variable binds; all stay quiet with no
per-fixture baseline."""

import jax
import numpy as np
from jax.sharding import Mesh

DCN = "dcn"
RACK = "rack"
HOST = "host"
DEVICE = "device"


def make_mesh(devices):
    return Mesh(
        np.array(devices).reshape(2, 2, 2, -1), (DCN, RACK, HOST, DEVICE)
    )


def combine(tree):
    # the flat twin of the tree combine: one psum over the FULL 4-tuple
    return jax.lax.psum(tree, (DCN, RACK, HOST, DEVICE))


def reduce_up(x):
    inner = (RACK, HOST, DEVICE)  # the sub-tree below the top hop
    return jax.lax.psum_scatter(x, inner, scatter_dimension=0, tiled=True)


def top_hop(x):
    return jax.lax.psum(x, ("dcn",))  # literal member of the declared tree


def gather_down(x):
    mid = (HOST, DEVICE)
    return jax.lax.all_gather(x, mid, axis=0, tiled=True)
