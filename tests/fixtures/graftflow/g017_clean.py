"""Clean twin of g017_violation.py: the same join-offer write and roster
read, but disciplined — the write lands on a tmp name and publishes with
atomic ``os.replace`` (readers see the old file or the new file, never a
torn one), and the read treats a missing or torn ack as absent. A raw
``json.dump`` to a NON-protocol path rides along to pin the rule's
scoping: only functions touching the rendezvous/heartbeat directory are
held to the discipline.
"""

import json
import os


def offer_join(rdzv_dir: str, ident: int) -> None:
    path = os.path.join(rdzv_dir, f"join_p{ident}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"ident": ident}, f)
    os.replace(tmp, path)


def read_roster(rdzv_dir: str):
    path = os.path.join(rdzv_dir, "ack_g0.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None  # missing or torn: legal at every protocol point


def save_report(report_path: str, stats: dict) -> None:
    # not a protocol file: plain json.dump is fine outside the rdzv dir
    with open(report_path, "w") as f:
        json.dump(stats, f)
