"""G011 negatives: the sanctioned donation idioms must stay quiet.

* restore with a FORCED copy before the device_put (the PR-6 fix shape)
* donate-and-rebind with no surviving alias
* donation in one If arm, read in the other (mutually exclusive)
"""

import jax
import jax.numpy as jnp

update = jax.jit(lambda state, grads: state - 0.1 * grads, donate_argnums=(0,))


def restore_checkpoint(mgr, step, sharding):
    restored = mgr.restore(step)
    # forced copy into a jax-owned buffer: donation-safe
    return jax.device_put(jnp.array(restored, copy=True), sharding)


def resume_and_step(mgr, step, sharding, grads):
    state = restore_checkpoint(mgr, step, sharding)
    state = update(state, grads)
    return state


def apply(state, grads):
    return update(state, grads)


def outer(state, grads):
    new = apply(state, grads)
    return new


def branches(state, grads, fast):
    if fast:
        out = update(state, grads)
    else:
        out = jnp.sum(state)  # other arm: the donate can't have run
    return out
