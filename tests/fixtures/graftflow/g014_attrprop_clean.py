"""Clean twin of g014_attrprop_violation: every attribute-valued axis
spelling resolves — a literal-returning property (axis the mesh defines), a
chained property, and the live-mesh ``axis_names`` derivation
(mesh_batch_axes-style: whatever it returns names axes the mesh actually
defines, so there is no unmet demand). All quiet."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh


def build_mesh(devices):
    return Mesh(np.array(devices), ("data",))


def mesh_batch_axes(mesh):
    names = tuple(mesh.axis_names)
    return names[0] if len(names) == 1 else names


class LiteralSteps:
    def __init__(self, mesh):
        self.mesh = mesh

    @property
    def _axis_arg(self):
        return "data"  # literal: joins the universe checks

    @property
    def _batch_entry(self):
        return self._axis_arg  # property chaining resolves through it

    def combine(self, grads):
        return jax.lax.psum(grads, self._axis_arg)

    def combine_chained(self, grads):
        return jax.lax.psum(grads, self._batch_entry)


class MeshDerivedSteps:
    def __init__(self, mesh):
        self.mesh = mesh

    @property
    def _axis_arg(self):
        # helper form: the value derives from the mesh's own axis_names
        return mesh_batch_axes(self.mesh)

    @property
    def _axis_arg_inline(self):
        # direct form of the same derivation
        names = tuple(self.mesh.axis_names)
        return names[0] if len(names) == 1 else names

    def combine(self, grads):
        return jax.lax.psum(grads, self._axis_arg)

    def combine_inline(self, grads):
        return jax.lax.psum(grads, self._axis_arg_inline)


def run(devices, grads):
    mesh = build_mesh(devices)
    a = LiteralSteps(mesh)
    b = MeshDerivedSteps(mesh)
    g = jnp.asarray(grads)
    return a.combine(g), a.combine_chained(g), b.combine(g), b.combine_inline(g)
