"""Seeded G018 violation (recovery phase order): the recovery path builds
the NEW world before the old one retired — ``establish`` runs while the
dead world's wedged collectives still hold the process-global launch
chain, so the first collective of the survivor mesh serializes behind (or
poisons itself against) half-dead gloo ops. The automaton extracted from
runtime/rendezvous.py orders flush -> agree -> drain/retire -> establish
-> reshard -> restore; this is the establish-before-teardown reorder the
graftrdzv model checker also catches dynamically (teardown-barrier
invariant).
"""


class Recovery:
    def flush_checkpoints(self):
        pass

    def agree(self, survivors):
        return list(survivors)

    def retire_runtime(self):
        pass

    def establish(self, survivors):
        pass

    def _reshard_world(self, survivors):
        pass

    def _state_from_host(self, host_state):
        return host_state

    def recover(self, survivors, host_state):
        self.flush_checkpoints()
        roster = self.agree(survivors)
        self.establish(roster)  # new world up while the old one still runs
        self.retire_runtime()  # phase 2 after phase 3: the reorder bug
        self._reshard_world(roster)
        return self._state_from_host(host_state)
