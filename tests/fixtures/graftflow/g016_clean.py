"""G016 negatives: the pad/quantize discipline.

* plan widths snapped by ``quantize_batches`` live on the bucket ladder —
  every worker's contribution is a fixed multiple of the bucket
* shards padded to the capacity width (``pad_to``/``_cap_b`` channel)
  before stacking are uniform by construction
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh


def make_mesh(devices):
    return Mesh(np.array(devices), ("data",))


def integer_batch_split(shares, global_batch):
    return np.maximum((shares * global_batch).astype(np.int64), 1)


def quantize_batches(batches, bucket, global_batch):
    return np.maximum(batches // bucket, 1) * bucket


def pack(parts, batch_sizes, pad_to):
    shards = [np.pad(p, (0, pad_to - len(p))) for p in parts]  # padded
    stacked = jnp.stack(shards)
    return jax.lax.all_gather(stacked, "data")


def gather_all(vec):
    return jax.lax.all_gather(vec, "data")


def epoch(shares, global_batch, bucket):
    batches = integer_batch_split(shares, global_batch)
    snapped = quantize_batches(batches, bucket, global_batch)
    return gather_all(snapped)
