"""G011 branch-sensitivity negative: the false positive PR 7's ROADMAP
recorded, now closed. The alias is bound in one If arm and the donation
happens in the OTHER — the two never coexist on any path, so the read
after the If is safe:

* fast path: ``snap = state`` but nothing donates
* slow path: ``state`` is donated-and-rebound, but ``snap`` was never
  bound to it (it holds the fresh zeros value)

Before branch-aware alias groups, the linear alias pass let the fast
path's ``snap = state`` survive into the slow path's donation analysis and
flagged the final read."""

import jax
import jax.numpy as jnp

step = jax.jit(lambda s, g: s - g, donate_argnums=(0,))


def window(state, grads, fastpath):
    if fastpath:
        snap = state  # alias on the non-donating path only
        out = jnp.sum(snap)
    else:
        snap = jnp.zeros(())
        state = step(state, grads)  # donation on the aliasing-free path
        out = jnp.sum(state)
    return state, out, snap
