"""G011 forwarding seeds: donation facts crossing the two channels PR 7's
ROADMAP recorded as modeling gaps, now closed.

Shape 1 (**kwargs forwarding): ``outer`` forwards its ``**kw`` verbatim to
``inner``, which donates its ``state`` parameter — so ``top``'s explicit
``state=state`` keyword dies at the call, and the later read is a
use-after-free positional argnums could never express.

Shape 2 (tree_map lambda): the donor is dispatched per-leaf from inside a
``jax.tree_util.tree_map`` lambda — the mapped TREES are donated, and the
alias taken before the map still points at the dead buffers.
"""

import jax
import jax.numpy as jnp

step = jax.jit(lambda s, g: s - g, donate_argnums=(0,))


def inner(state, batch):
    return step(state, batch)


def outer(**kw):
    return inner(**kw)


def top(state, batch):
    out = outer(state=state, batch=batch)
    return out, jnp.sum(state)  # donated through the ** forwarding chain


def leaf_update(s, g):
    return step(s, g)


def window(state, grads):
    snap = state  # alias taken before the per-leaf donation
    new = jax.tree_util.tree_map(lambda s, g: leaf_update(s, g), state, grads)
    return new, jnp.sum(snap)
