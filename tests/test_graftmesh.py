"""graftmesh (whole-program sharding/collective semantics) tests: the G014-
G016 rule families must trip on their seeded fixtures — including minimized
reproductions of BOTH motivating incidents (PR 6's restore-onto-the-old-mesh
placement, caught one function boundary deeper than G013 sees, and the
fused-AOT lowering-spec vs dispatch-seed placement mismatch) — the clean
twins must stay quiet, the MeshModel engine (axis universe, mesh-environment
lattice, required-axes fixpoint, spec identities) must hold its contracts,
and the pass must stay inside graftflow's runtime budget.
"""

import pathlib
import time

import pytest

from dynamic_load_balance_distributeddnn_tpu.analysis.flow import (
    CallGraph,
    Project,
    analyze_paths,
    analyze_source,
    summarize_source,
)
from dynamic_load_balance_distributeddnn_tpu.analysis.flow.mesh import (
    MeshModel,
)
from dynamic_load_balance_distributeddnn_tpu.analysis.linter import (
    lint_file,
    lint_paths,
)

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "graftflow"
REPO = pathlib.Path(__file__).resolve().parents[1]
PKG = REPO / "dynamic_load_balance_distributeddnn_tpu"


def codes(findings):
    return {f.code for f in findings}


def model_of(src: str, path: str = "m.py") -> MeshModel:
    proj = Project.from_summaries([summarize_source(src, path)])
    return MeshModel(proj, CallGraph(proj))


# ------------------------------------------------------------ seeded fixtures


@pytest.mark.parametrize(
    "fixture,expected_code,min_findings",
    [
        # unknown axis + shard_map supply/demand + elastic cfg size
        ("g014_violation.py", "G014", 4),
        # cross-boundary stale spec + lowering-vs-dispatch mismatch
        ("g015_violation.py", "G015", 2),
        # local unequal-shard sink + interprocedural param sink
        ("g016_violation.py", "G016", 3),
        # plan taint through self-attrs + container-element mutation
        # (ISSUE 11 satellite: the window controller stores plan-derived
        # sizes on `self` and packs columns into lists)
        ("g016_attr_violation.py", "G016", 3),
        # axis-param override channel must EXTEND the universe, not disarm
        # the rule (PR-12 satellite fixture pair)
        ("g014_override_violation.py", "G014", 1),
        # per-executable-key registered-lowering matching: a spec
        # registered for executable B must not sanction a mismatched
        # placement dispatched to executable A (PR-12 satellite)
        ("g015_key_violation.py", "G015", 1),
        # axis-tuple VARIABLES in collective axis args resolve through the
        # local bind — the hier combine's self._axis_arg class of
        # spellings no longer errs quiet (PR-13 satellite)
        ("g014_tuplevar_violation.py", "G014", 2),
        # plan taint through dict-VALUE iteration (.values() / .items()
        # tuple targets) — the last recorded modeling gap (PR-13 satellite)
        ("g016_dictval_violation.py", "G016", 2),
        # ATTRIBUTE-valued axis spellings (ISSUE 14 satellite): an opaque
        # self._axis_arg property is an explicit "unresolved axis
        # expression" finding, a literal-returning property feeds the
        # ordinary unknown-axis check, and an UNRELATED axis_names read in
        # the body must not silence an opaque return (review hardening)
        ("g014_attrprop_violation.py", "G014", 3),
        # N-tuple collective axes (ISSUE 17): the tree combine's 3- and
        # 4-member axis tuples resolve member-by-member — a typo'd middle
        # member, a stale sub-tuple bind, and an undeclared-level
        # axis_index all trip
        ("g014_ntuple_violation.py", "G014", 3),
    ],
)
def test_mesh_rule_trips_on_seeded_fixture(fixture, expected_code, min_findings):
    findings = analyze_paths([str(FIXTURES / fixture)])
    hits = [f for f in findings if f.code == expected_code]
    assert len(hits) >= min_findings, (fixture, findings)
    # a seeded fixture must not also trip unrelated flow rules (noise)
    assert codes(findings) == {expected_code}, findings
    # nor any single-file rule — each corpus file isolates ONE bug class
    assert lint_file(str(FIXTURES / fixture)) == []


@pytest.mark.parametrize(
    "fixture",
    [
        "g014_clean.py",
        "g015_clean.py",
        "g016_clean.py",
        "g016_attr_clean.py",
        "g014_override_clean.py",
        "g015_key_clean.py",
        "g014_tuplevar_clean.py",
        "g016_dictval_clean.py",
        "g014_attrprop_clean.py",
        "g014_ntuple_clean.py",
    ],
)
def test_clean_fixture_is_quiet(fixture):
    path = str(FIXTURES / fixture)
    assert analyze_paths([path]) == []
    assert lint_file(path) == []


def test_axis_param_override_extends_universe_and_value_env():
    """PR-12 satellite: a call-site literal override of a DEFAULTED axis
    param must enter the axis universe AND the bound mesh's value
    environment — previously invisible, so every collective over the
    override axis was a false G014."""
    src = (
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def build(devices, axis='data'):\n"
        "    return Mesh(np.array(devices), (axis,))\n"
        "def use(devices):\n"
        "    mesh = build(devices, axis='model')\n"
        "    return mesh\n"
    )
    model = model_of(src)
    assert model.axis_universe == {"data", "model"}
    assert model.axis_universe_complete
    fn = model.project.functions["m::use"]
    assert model.mesh_axes_of_token(fn, "mesh") == {"model"}
    # the callee's own default-resolved return is unchanged
    assert model.mesh_returns["m::build"] == frozenset({"data"})


def test_axis_tuple_variable_resolves_through_local_bind():
    """PR-13 satellite: a collective whose axis argument is a VARIABLE
    bound to a tuple (or string) literal resolves through the local bind —
    constants in the tuple resolve too; attribute-valued binds and later
    opaque rebinds stay unresolved (errs quiet)."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "H = 'host'\n"
        "def make(devices):\n"
        "    return Mesh(np.array(devices), (H, 'device'))\n"
        "def combine(x):\n"
        "    axes = (H, 'device')\n"
        "    return jax.lax.psum(x, axes)\n"
        "def strvar(x):\n"
        "    ax = 'host'\n"
        "    return jax.lax.axis_index(ax) + x\n"
        "def opaque(obj, x):\n"
        "    axes = obj.batch_axes\n"
        "    return jax.lax.psum(x, axes)\n"
        "def rebound(obj, x):\n"
        "    axes = (H,)\n"
        "    axes = obj.batch_axes\n"
        "    return jax.lax.psum(x, axes)\n"
    )
    model = model_of(src)
    assert model.required_axes["m::combine"] == {"host", "device"}
    assert model.required_axes["m::strvar"] == {"host"}
    assert model.required_axes["m::opaque"] == set()
    assert model.required_axes["m::rebound"] == set()  # rebind forgets


def test_attr_axis_property_resolution_channels():
    """ISSUE 14 satellite: ``self.<attr>`` collective-axis spellings
    resolve through simple property returns — a literal joins the demand,
    a chained property resolves through its target, a live-mesh
    ``axis_names`` derivation contributes no demand (consistent by
    construction), and an opaque property lands in
    ``unresolved_axis_sites`` instead of erring quiet."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def make(devices):\n"
        "    return Mesh(np.array(devices), ('data',))\n"
        "def batch_axes(mesh):\n"
        "    names = tuple(mesh.axis_names)\n"
        "    return names[0] if len(names) == 1 else names\n"
        "class Steps:\n"
        "    def __init__(self, mesh):\n"
        "        self.mesh = mesh\n"
        "    @property\n"
        "    def lit(self):\n"
        "        return 'data'\n"
        "    @property\n"
        "    def chained(self):\n"
        "        return self.lit\n"
        "    @property\n"
        "    def derived(self):\n"
        "        return batch_axes(self.mesh)\n"
        "    @property\n"
        "    def opaque(self):\n"
        "        return ''.join(['da', 'ta'])\n"
        "    def c_lit(self, x):\n"
        "        return jax.lax.psum(x, self.lit)\n"
        "    def c_chained(self, x):\n"
        "        return jax.lax.psum(x, self.chained)\n"
        "    def c_derived(self, x):\n"
        "        return jax.lax.psum(x, self.derived)\n"
        "    def c_opaque(self, x):\n"
        "        return jax.lax.psum(x, self.opaque)\n"
    )
    model = model_of(src)
    assert model.required_axes["m::Steps.c_lit"] == {"data"}
    assert model.required_axes["m::Steps.c_chained"] == {"data"}
    assert model.required_axes["m::Steps.c_derived"] == set()
    assert model.required_axes["m::Steps.c_opaque"] == set()
    sites = [
        (fqn, tok) for fqn, _l, _c, _t, tok in model.unresolved_axis_sites
    ]
    assert sites == [("m::Steps.c_opaque", "self.opaque")]


def test_two_level_axis_universe_and_tuple_collectives():
    """ISSUE 12: the (host, device) factorization is modeled — the hier
    mesh helper's constants enter the universe, and a tuple-literal
    collective axis (``psum(x, ("host", "device"))``, the two-level
    combine's spelling) demands BOTH member axes."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "HOST_AXIS = 'host'\n"
        "DEVICE_AXIS = 'device'\n"
        "def hier_mesh(devices, hosts, host_axis=HOST_AXIS,"
        " device_axis=DEVICE_AXIS):\n"
        "    arr = np.array(devices)\n"
        "    return Mesh(arr, (host_axis, device_axis))\n"
        "def combine(tree):\n"
        "    return jax.lax.psum(tree, ('host', 'device'))\n"
        "def hop(v):\n"
        "    return jax.lax.psum(v, 'host')\n"
    )
    model = model_of(src)
    assert {"host", "device"} <= model.axis_universe
    assert model.required_axes["m::combine"] == {"host", "device"}
    assert model.required_axes["m::hop"] == {"host"}


def test_g015_key_scoping_narrows_but_falls_back_class_wide():
    """Per-executable-key matching: key literals are harvested only from
    registry-call tuple arguments, a keyed dispatch checks against its own
    key's scopes, and a key-less dispatch keeps the class-wide union."""
    from dynamic_load_balance_distributeddnn_tpu.analysis.flow.mesh import (
        RuleG015,
    )

    viol = (FIXTURES / "g015_key_violation.py").read_text()
    clean = (FIXTURES / "g015_key_clean.py").read_text()
    proj = Project.from_summaries([summarize_source(viol, "v.py")])
    lits = RuleG015._key_literals(
        [proj.functions["v::Engine._submit_fused"]]
    )
    assert lits == {"fused"}
    assert RuleG015._key_literals(
        [proj.functions["v::Engine._dispatch_fused"]]
    ) == {"fused"}
    assert [f.code for f in analyze_source(viol)] == ["G015"]
    assert analyze_source(clean) == []


def test_g015_flags_restore_onto_old_mesh_across_boundary():
    """ISSUE acceptance (a): the PR-6 restore-onto-the-old-mesh placement,
    minimized with the spec obtained THROUGH a helper so G013's local
    mesh-capture rule is blind — exactly one of G014-G016 must flag it."""
    findings = analyze_paths([str(FIXTURES / "g015_violation.py")])
    stale = [f for f in findings if "STALE" in f.message]
    assert stale, findings
    assert stale[0].symbol.endswith("Engine.resume")
    assert codes(findings) == {"G015"}


def test_g015_flags_lowering_vs_dispatch_mismatch():
    """ISSUE acceptance (b): the fused-AOT lowering-spec vs dispatch-seed
    placement mismatch — the dispatch placement's spec identity is not in
    the class's registered lowering set."""
    findings = analyze_paths([str(FIXTURES / "g015_violation.py")])
    mism = [f for f in findings if "registered" in f.message]
    assert mism, findings
    assert mism[0].symbol.endswith("Engine")


# ----------------------------------------------------------- MeshModel units


def test_axis_universe_resolves_constants_and_param_defaults():
    src = (
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        'DATA_AXIS = "data"\n'
        "def data_mesh(devices, axis=DATA_AXIS):\n"
        "    return Mesh(np.array(devices), (axis,))\n"
        "def build(devices):\n"
        "    return data_mesh(devices)\n"
    )
    model = model_of(src)
    assert model.axis_universe == {"data"}
    # the helper's defaulted axis resolves through the constant table
    assert model.helper_axis_default["data_mesh"] == "data"


def test_unknown_collective_axis_fires_and_known_is_quiet():
    base = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def make(devices):\n"
        '    return Mesh(np.array(devices), ("data",))\n'
        "def combine(tree):\n"
        '    return jax.lax.psum(tree, "{axis}")\n'
    )
    bad = analyze_source(base.format(axis="dat"))
    assert codes(bad) == {"G014"}, bad
    assert analyze_source(base.format(axis="data")) == []


def test_one_finding_per_typoed_spec():
    """The same bad construction surfaces through bind.spec, its CallFact,
    the nested P call, and spec_args — exactly ONE finding must emerge."""
    src = (
        "import numpy as np\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "def make(devices):\n"
        '    return Mesh(np.array(devices), ("data",))\n'
        "def build(mesh):\n"
        '    s = NamedSharding(mesh, P("dat"))\n'
        "    return s\n"
    )
    findings = analyze_source(src)
    assert [f.code for f in findings] == ["G014"], findings


def test_incomplete_axis_universe_stays_quiet():
    """A mesh construction with dynamic (unresolvable) axes marks the
    universe incomplete: membership checks must not guess — the dropped
    mesh may define any axis (the errs-quiet contract)."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def make(devices):\n"
        '    return Mesh(np.array(devices), ("data",))\n'
        "def make_dyn(devices, names):\n"
        "    return Mesh(np.array(devices), names)\n"
        "def combine(tree):\n"
        '    return jax.lax.psum(tree, "model")\n'
    )
    assert analyze_source(src) == []


def test_mesh_param_lattice_joins_over_call_sites():
    """A mesh-typed parameter's axes are the union of every mesh its
    resolved callers pass — the mesh-environment lattice join."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def use(mesh):\n"
        "    return mesh\n"
        "def a(devices):\n"
        '    m = Mesh(np.array(devices), ("data",))\n'
        "    return use(m)\n"
        "def b(devices):\n"
        '    m = Mesh(np.array(devices), ("data", "model"))\n'
        "    return use(m)\n"
    )
    model = model_of(src)
    assert model.param_mesh_axes[("m::use", "mesh")] == {"data", "model"}


def test_mesh_returns_resolve_through_wrapper_chains():
    """``get()`` forwarding ``make()``'s mesh must still supply axes to the
    shard_map check — the fixpoint chases call edges, not just direct
    constructions."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def make(devices):\n"
        '    return Mesh(np.array(devices), ("data",))\n'
        "def get(devices):\n"
        "    m = make(devices)\n"
        "    return m\n"
        "def body(x):\n"
        '    return jax.lax.psum(x, "model")\n'
        "def wire(devices):\n"
        "    mesh = get(devices)\n"
        "    return jax.shard_map(body, mesh=mesh, in_specs=None, out_specs=None)\n"
    )
    model = model_of(src)
    assert model.mesh_returns["m::get"] == frozenset({"data"})
    findings = analyze_source(src)
    assert any("shard_map" in f.message for f in findings), findings


def test_mesh_resolution_stops_at_the_use_site():
    """A mesh rebind AFTER a shard_map must not shadow the mesh the call
    actually received — local resolution is bounded by the use line."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def body(x):\n"
        '    return jax.lax.psum(x, "model")\n'
        "def wire(devices, sub):\n"
        '    mesh = Mesh(np.array(devices), ("data", "model"))\n'
        "    out = jax.shard_map(body, mesh=mesh, in_specs=None, out_specs=None)\n"
        '    mesh = Mesh(np.array(sub), ("data",))\n'
        "    return out, mesh\n"
    )
    assert analyze_source(src) == []


def test_g015_helper_obtained_registration_specs_count():
    """Registration symmetry: a spec lowered under a spec-returning helper
    (the sds/win_spec idiom) is registered — dispatching under the same
    helper's spec must not flag."""
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "class Engine:\n"
        "    def _sh(self):\n"
        '        return NamedSharding(self.mesh, P("data"))\n'
        "    def _submit_aot(self, state):\n"
        "        seed_t = jax.ShapeDtypeStruct(\n"
        "            (), jnp.int32, sharding=NamedSharding(self.mesh, P()))\n"
        "        win = self._sh()\n"
        "        win_t = jax.ShapeDtypeStruct((4,), jnp.int32, sharding=win)\n"
        '        self._aot.submit(("fused", 0), state, (seed_t, win_t))\n'
        "    def _dispatch(self, x):\n"
        "        sp = self._sh()\n"
        "        return jax.device_put(x, sp)\n"
    )
    assert analyze_source(src) == []


def test_required_axes_propagate_bottom_up():
    src = (
        "import jax\n"
        "def leaf(x):\n"
        '    return jax.lax.psum(x, "data")\n'
        "def mid(x):\n"
        "    return leaf(x)\n"
        "def top(x):\n"
        "    return mid(x)\n"
    )
    model = model_of(src)
    assert model.required_axes["m::top"] == {"data"}


def test_shard_map_over_partial_wrapped_target():
    """The repo idiom: shard_map(functools.partial(fn, ...), mesh=...) —
    the partial's bound callable is the demand side."""
    src = (
        "import jax\n"
        "import functools\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def body(x, causal=True):\n"
        '    return jax.lax.psum(x, "model")\n'
        "def wire(devices):\n"
        '    mesh = Mesh(np.array(devices), ("data", "model"))\n'
        '    small = Mesh(np.array(devices), ("data",))\n'
        "    return jax.shard_map(\n"
        "        functools.partial(body, causal=False),\n"
        "        mesh=small, in_specs=None, out_specs=None)\n"
    )
    findings = analyze_source(src)
    assert any(
        f.code == "G014" and "shard_map" in f.message for f in findings
    ), findings


def test_elastic_reshard_axis_rebind_unit():
    """The elastic contract: _reshard_world rebuilds the mesh from RUNTIME
    state. Sizing a placed vector from self.world_size (which the re-shard
    rebinds) is clean; sizing it from cfg.world_size fires."""
    base = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "class Engine:\n"
        "    def __init__(self, cfg, devices):\n"
        "        self.cfg = cfg\n"
        "        self.world_size = cfg.world_size\n"
        '        self.mesh = Mesh(np.array(devices), ("data",))\n'
        "    def _reshard_world(self, active):\n"
        "        self.world_size = len(active)\n"
        '        self.mesh = Mesh(np.array(active), ("data",))\n'
        "    def stage(self):\n"
        "        slow = np.zeros({size}, np.int32)\n"
        "        return jax.device_put(slow, NamedSharding(self.mesh, P()))\n"
    )
    clean = base.format(size="self.world_size")
    assert analyze_source(clean) == [], analyze_source(clean)
    dirty = base.format(size="self.cfg.world_size")
    findings = analyze_source(dirty)
    assert any(
        f.code == "G014" and "world_size" in f.message for f in findings
    ), findings


def test_world_size_gated_placement_is_not_a_sizing():
    """Gating a placement on cfg.world_size is not SIZING by it — the sink
    only fires when its own arguments carry the cfg-sized value."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "class Engine:\n"
        "    def __init__(self, cfg, devices):\n"
        "        self.cfg = cfg\n"
        '        self.mesh = Mesh(np.array(devices), ("data",))\n'
        "    def _reshard_world(self, active):\n"
        '        self.mesh = Mesh(np.array(active), ("data",))\n'
        "    def place(self, x):\n"
        "        sh = NamedSharding(self.mesh, P())\n"
        "        return jax.device_put(x, sh) if self.cfg.world_size > 1 else x\n"
    )
    assert analyze_source(src) == []


def test_spec_returns_cross_function_resolution():
    src = (
        "import numpy as np\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "class E:\n"
        "    def _sh(self):\n"
        '        return NamedSharding(self.mesh, P("data"))\n'
        "    def _sh2(self):\n"
        "        s = self._sh()\n"
        "        return s\n"
    )
    model = model_of(src)
    assert model.spec_returns["m::E._sh"] == (("sharding", ("data",)), True)
    assert model.spec_returns["m::E._sh2"] == (("sharding", ("data",)), True)


def test_g015_gen_keyed_placement_is_sanctioned():
    """A placement whose statement carries the _aot_gen generation marker
    is sanctioned — the same model G013 uses (stale keys can never hit)."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "class Engine:\n"
        "    def _sh(self):\n"
        "        return NamedSharding(self.mesh, P())\n"
        "    def _reshard_world(self, active):\n"
        '        self.mesh = Mesh(np.array(active), ("data",))\n'
        "        self._aot_gen += 1\n"
        "    def resume(self, ckpt, active):\n"
        "        sh = self._sh()\n"
        "        self._reshard_world(active)\n"
        "        return jax.device_put(ckpt.state, sh), self._aot_gen\n"
    )
    assert analyze_source(src) == []
    # and without the marker it fires
    bare = src.replace(", self._aot_gen\n", "\n")
    assert codes(analyze_source(bare)) == {"G015"}


def test_g016_cleanse_through_quantize_markers():
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def make(devices):\n"
        '    return Mesh(np.array(devices), ("data",))\n'
        "def epoch(shares, global_batch, bucket):\n"
        "    batches = integer_batch_split(shares, global_batch)\n"
        "    snapped = quantize_batches(batches, bucket, global_batch)\n"
        '    return jax.lax.all_gather(snapped, "data")\n'
    )
    assert analyze_source(src) == []
    raw = src.replace(
        "snapped = quantize_batches(batches, bucket, global_batch)",
        "snapped = batches",
    )
    assert codes(analyze_source(raw)) == {"G016"}


def test_g016_interprocedural_param_sink():
    """The taint and the collective live in different functions: the
    finding lands at the CALL site handing the raw plan widths over."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def make(devices):\n"
        '    return Mesh(np.array(devices), ("data",))\n'
        "def gather_all(vec):\n"
        '    return jax.lax.all_gather(vec, "data")\n'
        "def epoch(shares, global_batch):\n"
        "    batches = integer_batch_split(shares, global_batch)\n"
        "    return gather_all(batches)\n"
    )
    findings = analyze_source(src)
    assert [f.code for f in findings] == ["G016"], findings
    assert findings[0].line == 10


def test_g016_taint_climbs_multi_level_call_chains():
    """A param handed straight into a callee's sink position keeps the
    chain climbing: top -> mid -> helper -> all_gather still flags top."""
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def make(devices):\n"
        '    return Mesh(np.array(devices), ("data",))\n'
        "def helper(x):\n"
        '    return jax.lax.all_gather(x, "data")\n'
        "def mid(v):\n"
        "    return helper(v)\n"
        "def top(shares, global_batch):\n"
        "    batches = integer_batch_split(shares, global_batch)\n"
        "    return mid(batches)\n"
    )
    findings = analyze_source(src)
    assert [f.code for f in findings] == ["G016"], findings
    assert findings[0].line == 12


def test_g016_taint_flows_through_self_attrs():
    """ISSUE 11 satellite: a plan-derived value stored on ``self`` in one
    method and sunk in ANOTHER method of the same class must flag — and the
    quantized twin must stay quiet (cleanse at the attr write)."""
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def make(devices):\n"
        '    return Mesh(np.array(devices), ("data",))\n'
        "class Ctl:\n"
        "    def plan(self, shares, global_batch):\n"
        "        self._sizes = integer_batch_split(shares, global_batch)\n"
        "    def flush(self, parts):\n"
        "        cols = [p[:b] for p, b in zip(parts, self._sizes)]\n"
        "        return jnp.stack(cols)\n"
    )
    findings = analyze_source(src)
    assert codes(findings) == {"G016"}, findings
    clean = src.replace(
        "self._sizes = integer_batch_split(shares, global_batch)",
        "self._sizes = quantize_batches(\n"
        "            integer_batch_split(shares, global_batch), 8, global_batch)",
    )
    assert analyze_source(clean) == []


def test_g016_taint_flows_through_container_mutation():
    """``cols.append(batches)`` then ``jnp.stack(cols)`` is the same bug as
    stacking the raw widths directly — mutation taints the receiver (local
    containers and self-attr containers alike); appending a quantized value
    stays quiet."""
    src = (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def make(devices):\n"
        '    return Mesh(np.array(devices), ("data",))\n'
        "def epoch(shares, global_batch):\n"
        "    cols = []\n"
        "    batches = integer_batch_split(shares, global_batch)\n"
        "    cols.append(batches)\n"
        "    return jnp.stack(cols)\n"
    )
    findings = analyze_source(src)
    assert codes(findings) == {"G016"}, findings
    clean = src.replace(
        "cols.append(batches)",
        "cols.append(quantize_batches(batches, 8, global_batch))",
    )
    assert analyze_source(clean) == []


def test_g016_subscript_store_unions_container_taint():
    """An element store into a container neither replaces nor (when clean)
    un-taints it: ``d[0] = raw`` taints, and a later clean element store
    must not wash the earlier taint away."""
    src = (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def make(devices):\n"
        '    return Mesh(np.array(devices), ("data",))\n'
        "def epoch(shares, global_batch, other):\n"
        "    cols = {}\n"
        "    cols[0] = integer_batch_split(shares, global_batch)\n"
        "    cols[1] = other\n"
        "    return jnp.stack(list(cols.values()))\n"
    )
    findings = analyze_source(src)
    assert codes(findings) == {"G016"}, findings


def test_inline_suppression_silences_mesh_findings():
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh\n"
        "def make(devices):\n"
        '    return Mesh(np.array(devices), ("data",))\n'
        "def combine(tree):\n"
        '    return jax.lax.psum(tree, "dcn")  # graftlint: disable=G014\n'
    )
    assert analyze_source(src) == []


# ------------------------------------------------- runtime budget (tier-1)


def test_mesh_self_runtime_budget(tmp_path):
    """ISSUE acceptance: the full-repo --flow run including G014-G016 must
    stay within 2x of graftflow's budget (cold) and the cached warm run
    decisively under it. Bounds mirror tests/test_graftflow.py."""
    cache = str(tmp_path / "cache")
    t0 = time.perf_counter()
    cold = lint_paths(
        [str(PKG), str(REPO / "bench.py")], jobs=0, cache_dir=cache, flow=True
    )
    cold_s = time.perf_counter() - t0
    assert cold_s < 120.0, f"cold full-repo --flow took {cold_s:.1f}s"
    t0 = time.perf_counter()
    warm = lint_paths(
        [str(PKG), str(REPO / "bench.py")], jobs=0, cache_dir=cache, flow=True
    )
    warm_s = time.perf_counter() - t0
    assert warm_s < 60.0, f"warm full-repo --flow took {warm_s:.1f}s"
    key = lambda fs: [(f.code, f.path, f.line, f.message) for f in fs]
    assert key(cold) == key(warm)
