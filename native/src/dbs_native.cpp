// dbs_native — the framework's native host runtime.
//
// The reference delegates its host-side runtime to PyTorch internals: the
// DataLoader's native worker pool materializes per-step batches
// (dataloader.py:105-117 in the reference) and the replicated DBS solver runs
// as numpy (dbs.py:458-476). Here those host-path pieces are first-party C++:
//
//   * dbs_gather_rows       — multithreaded row gather/pack: materializes a
//                             worker's whole epoch ([steps, padded_batch] index
//                             plan -> packed contiguous batches) from the
//                             host-resident dataset. This is the per-epoch host
//                             hot path that feeds the TPU; threads saturate
//                             host memory bandwidth where numpy fancy-indexing
//                             is single-threaded.
//   * dbs_integer_batch_split / dbs_rebalance
//                           — the DBS partition solver (inverse-time update +
//                             the reference's exact integer rounding rule,
//                             dbs.py:458-476), bit-for-bit equal to the Python
//                             implementation in balance/solver.py (parity is
//                             pytest-enforced).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
// All functions return 0 on success, negative on argument errors.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Row gather: out[i] = data[idx[i]] for row_bytes-sized rows.
//
// data      : base pointer of a C-contiguous [n_rows, row_bytes] array
// n_rows    : number of source rows (bounds-checked)
// row_bytes : bytes per row (image: H*W*C for uint8; labels: 4)
// idx       : n_idx row indices (int64). Negative or >= n_rows -> error -2.
// out       : preallocated n_idx * row_bytes bytes
// n_threads : 0 -> hardware_concurrency
int dbs_gather_rows(const void* data, int64_t n_rows, int64_t row_bytes,
                    const int64_t* idx, int64_t n_idx, void* out,
                    int n_threads) {
  if (data == nullptr || idx == nullptr || out == nullptr) return -1;
  if (n_rows < 0 || row_bytes <= 0 || n_idx < 0) return -1;

  const auto* src = static_cast<const unsigned char*>(data);
  auto* dst = static_cast<unsigned char*>(out);

  // Bounds pre-check so worker threads can memcpy unconditionally.
  for (int64_t i = 0; i < n_idx; ++i) {
    if (idx[i] < 0 || idx[i] >= n_rows) return -2;
  }

  unsigned hw = std::thread::hardware_concurrency();
  int64_t want = n_threads > 0 ? n_threads : (hw ? static_cast<int64_t>(hw) : 4);
  // Below ~4 MiB of traffic the spawn cost dominates; stay single-threaded.
  const int64_t total_bytes = n_idx * row_bytes;
  if (total_bytes < (4 << 20)) want = 1;
  const int64_t nt = std::min<int64_t>(want, std::max<int64_t>(n_idx, 1));

  if (nt <= 1) {
    for (int64_t i = 0; i < n_idx; ++i) {
      std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
                  static_cast<size_t>(row_bytes));
    }
    return 0;
  }

  std::vector<std::thread> threads;
  threads.reserve(static_cast<size_t>(nt));
  const int64_t chunk = (n_idx + nt - 1) / nt;
  for (int64_t t = 0; t < nt; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = std::min(n_idx, lo + chunk);
    if (lo >= hi) break;
    threads.emplace_back([=]() {
      for (int64_t i = lo; i < hi; ++i) {
        std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
                    static_cast<size_t>(row_bytes));
      }
    });
  }
  for (auto& th : threads) th.join();
  return 0;
}

// ---------------------------------------------------------------------------
// Integer batch split (reference dbs.py:465-473; balance/solver.py).
//
// floor(share_i/sum * B), then +1 only to indices that are BOTH in the
// top-(B - sum_floor) fractional remainders (stable ascending sort, take the
// tail — matching np.argsort(kind="stable")[-short:]) AND have remainder
// >= 0.5. Sum of the result may be < B by design.
int dbs_integer_batch_split(const double* shares, int n, int64_t global_batch,
                            int64_t* out_batches) {
  if (shares == nullptr || out_batches == nullptr || n <= 0) return -1;
  double total = 0.0;
  for (int i = 0; i < n; ++i) total += shares[i];
  if (!(total > 0.0)) return -2;

  std::vector<double> remainder(n);
  int64_t floor_sum = 0;
  for (int i = 0; i < n; ++i) {
    const double ideal = shares[i] * static_cast<double>(global_batch) / total;
    const double fl = std::floor(ideal);
    out_batches[i] = static_cast<int64_t>(fl);
    remainder[i] = ideal - fl;
    floor_sum += out_batches[i];
  }
  const int64_t short_by = global_batch - floor_sum;
  if (short_by > 0) {
    std::vector<int> order(n);
    std::iota(order.begin(), order.end(), 0);
    std::stable_sort(order.begin(), order.end(), [&](int a, int b) {
      return remainder[a] < remainder[b];
    });
    const int64_t k = std::min<int64_t>(short_by, n);
    for (int64_t j = n - k; j < n; ++j) {
      const int i = order[j];
      if (remainder[i] >= 0.5) out_batches[i] += 1;
    }
  }
  return 0;
}

// ---------------------------------------------------------------------------
// One DBS rebalance step (reference dbs.py:458-476; balance/solver.py).
//
// r_i = (p_i/t_i) / sum_j(p_j/t_j), optional share cap with pro-rata
// redistribution (max_share <= 0 disables), then the integer split above and
// renormalization over the integer batches.
int dbs_rebalance(const double* node_times, const double* shares, int n,
                  int64_t global_batch, double max_share, double* out_shares,
                  int64_t* out_batches) {
  if (node_times == nullptr || shares == nullptr || out_shares == nullptr ||
      out_batches == nullptr || n <= 0)
    return -1;
  for (int i = 0; i < n; ++i) {
    if (!(node_times[i] > 0.0)) return -2;
  }

  std::vector<double> r(n);
  double speed_sum = 0.0;
  for (int i = 0; i < n; ++i) {
    r[i] = shares[i] / node_times[i];
    speed_sum += r[i];
  }
  if (!(speed_sum > 0.0)) return -2;
  for (int i = 0; i < n; ++i) r[i] /= speed_sum;

  if (max_share > 0.0) {
    if (max_share * n < 1.0) return -3;
    std::vector<unsigned char> over(n);
    for (int round = 0; round < n; ++round) {
      double excess = 0.0, free_sum = 0.0;
      bool any_over = false;
      for (int i = 0; i < n; ++i) {
        over[i] = r[i] > max_share ? 1 : 0;
        if (over[i]) {
          excess += r[i] - max_share;
          r[i] = max_share;
          any_over = true;
        } else {
          free_sum += r[i];
        }
      }
      if (!any_over) break;
      // Redistribute pro-rata over everything not over-cap THIS round —
      // including entries sitting exactly at the cap (they get topped up and
      // re-clamped next round), matching balance/solver.py's `free = ~over`.
      if (free_sum > 0.0) {
        for (int i = 0; i < n; ++i) {
          if (!over[i]) r[i] += excess * r[i] / free_sum;
        }
      }
    }
  }

  int rc = dbs_integer_batch_split(r.data(), n, global_batch, out_batches);
  if (rc != 0) return rc;
  int64_t total = 0;
  for (int i = 0; i < n; ++i) total += out_batches[i];
  if (total <= 0) return -4;
  for (int i = 0; i < n; ++i)
    out_shares[i] =
        static_cast<double>(out_batches[i]) / static_cast<double>(total);
  return 0;
}

// ---------------------------------------------------------------------------
// Version/capability probe so the Python loader can verify ABI.
int dbs_native_abi_version() { return 1; }

}  // extern "C"
