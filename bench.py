#!/usr/bin/env python
"""Headline benchmark — prints ONE JSON line.

North-star scenario (BASELINE.json / reference README.md:23-28): DenseNet-121
on CIFAR-10, world_size=4, global batch 512, induced 3:1 straggler on worker 0
(real on-device compute, fault_mode='compute'), DBS on vs off (the A/B of
run.sh:25-41). Metric: steady-state epoch wall-clock with DBS on;
vs_baseline: speedup over the DBS-off arm (>1 = the balancer wins).

Resilience design (from measured behavior of this host's TPU tunnel: backend
init can block 50+ minutes and then fail UNAVAILABLE):

1. PREFLIGHT LADDER — a standalone subprocess inits the backend with
   escalating timeouts (BENCH_PREFLIGHT_TIMEOUTS, default 600,1500,2400s),
   retrying until the reserve deadline. Arms never burn attempts on a wedged
   runtime.
2. CPU INSURANCE — after the first failed preflight, a small CPU-mesh A/B
   (same code path, virtual 4-device mesh, compute-mode straggler) runs so a
   clearly-labeled fallback number exists; preflight then continues, and a
   real TPU result overwrites the insurance.
3. ONE INIT FOR BOTH ARMS — both arms run in a single subprocess (one
   backend claim), writing per-epoch walls incrementally; a crash mid-run
   leaves salvageable partials. Retries shrink BENCH_NTRAIN (compile cache
   persists across attempts via JAX_COMPILATION_CACHE_DIR).
4. EARLY EXIT — SIGTERM/SIGINT print the best result so far before dying,
   AND every improvement (including the pre-preflight disk-derived seed) is
   printed as a JSON line the moment it exists, so even an unhandleable
   SIGKILL mid-ladder leaves the best-so-far as the final parsed line.
   When NO prior artifact exists (rounds 4/5 both started cold and round 5
   died at rc=124 with `parsed: null`), an explicit `{"status":"no_result"}`
   floor line is printed before anything can eat the budget — the driver
   always parses SOMETHING, and any later improvement supersedes the floor.
5. AOT WARM A/B — the CPU tier also measures the serial execute-to-compile
   warm wall vs the concurrent AOT compile service (`aot_warm_ab` field,
   dedicated subprocess with per-program-serial codegen; ISSUE 3).
6. TRACE OVERHEAD A/B — the CPU tier measures graftscope span tracing's
   wall cost (`trace_overhead_ab`: --trace on vs off on the same elastic
   plan; the traced leg writes the Chrome-trace JSON and reports per-phase
   epoch attribution + worst-epoch coverage; ISSUE 4, BENCH_TRACE_AB=0
   disables).
7. COMPILE WORKERS A/B — the CPU tier measures multi-program compile
   throughput through the AOT service's process-worker backend vs the
   in-process thread pool (`compile_workers_ab` field: the same eight
   resnet18 worker-step programs, equal compile counts, thread leg first
   on a disabled persistent cache; ISSUE 5, BENCH_WORKERS_AB=0 disables).
8. ELASTIC RECOVERY A/B — the CPU tier kills 1 of ws workers mid-run via
   the PreemptionInjector and measures detection-to-resumed-training time
   plus the post-recovery steady epoch wall vs a fresh run started at the
   reduced world size (`elastic_recovery_ab` field; ISSUE 6,
   BENCH_ELASTIC_AB=0 disables).
9. ONLINE DBS A/B — the CPU tier runs the SAME time-varying compute-mode
   straggler (sin schedule over a 5:1 profile) under window-cadence
   rebalancing (the hysteresis controller switches plans mid-epoch) vs the
   reference epoch cadence (`online_dbs_ab` field: steady epoch walls,
   switch counts, controller ledger, realized injection; ISSUE 11,
   BENCH_ONLINE_AB=0 disables, BENCH_ONLINE_SCHEDULE/PERIOD/EPOCHS tune).
10. FLIGHT RECORDER A/B — the CPU tier measures the crash-durable spool's
   wall cost (`obs_overhead_ab`: --trace ring + --trace_spool vs trace-off
   on the same elastic plan, budget <= 5%, spool bytes/step recorded;
   ISSUE 15, BENCH_OBS_AB=0 disables).

Instrumentation: examples/s and MFU (obs/flops.py, XLA cost model vs chip
bf16 peak) from the trainer's recorder extras, reported in `detail`.

Knobs: BENCH_NTRAIN (12800), BENCH_EPOCHS (7), BENCH_WS (4), BENCH_RETRIES
(3), BENCH_STALL_S (900s, in-subprocess heartbeat-stall watchdog),
BENCH_TOTAL_BUDGET (5400s), BENCH_ARM_RESERVE (1800s),
BENCH_INIT_TIMEOUT (2700s, in-subprocess init watchdog),
BENCH_PREFLIGHT_TIMEOUTS, BENCH_FORCE_CPU=1 (skip TPU entirely),
BENCH_CPU_INSURANCE=0 (disable the fallback).
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

# Persistent XLA compilation cache, shared by EVERY subprocess this file
# spawns (preflight attempts, arm runs, retries across shrink levels): the
# path is made absolute (a child changing cwd must not fork the cache) and
# the min-compile-time/entry-size floors are zeroed so preflight's tiny
# matmul and the small CPU-tier programs persist too — preflight attempt 2
# used to recompile everything attempt 1 had already paid for.
_cache_dir = os.path.abspath(os.environ.get("JAX_COMPILATION_CACHE_DIR") or "./.jax_cache")
try:
    os.makedirs(_cache_dir, exist_ok=True)
except OSError:
    pass
os.environ["JAX_COMPILATION_CACHE_DIR"] = _cache_dir
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")

_best_result = None  # orchestrator's best-known JSON dict


# --------------------------------------------------------------- subprocesses


def _install_init_watchdog():
    """Hard-exit if backend init blocks past BENCH_INIT_TIMEOUT. The hang is
    inside PJRT C++ where Python signal handlers never run, so a daemon
    thread + os._exit is the only reliable abort."""
    import threading

    done = threading.Event()

    def _watchdog():
        if not done.wait(int(os.environ.get("BENCH_INIT_TIMEOUT", 2700))):
            sys.stderr.write("[bench] backend init timed out; aborting\n")
            sys.stderr.flush()
            os._exit(17)

    threading.Thread(target=_watchdog, daemon=True).start()
    return done


def run_preflight(light: bool = False) -> int:
    """Init the backend, run one tiny matmul, report device info. rc 0 = the
    TPU is usable; rc 17 = init watchdog fired; other rc = init raised.

    ``light`` is attempt 1's shrunk profile (rounds 4/5 died rc=124 with the
    ladder still inside attempt 1): the init watchdog is capped INSIDE the
    attempt's own 600 s budget — the default 2700 s watchdog meant a wedged
    init could only be ended by the parent's kill, eating the whole cap —
    and the matmul compile is skipped (first contact with a cold persistent
    cache + remote-compile tunnel is the slow path). A light pass proves the
    runtime answers; the full pass on the next rung proves it computes."""
    if light:
        os.environ["BENCH_INIT_TIMEOUT"] = os.environ.get(
            "BENCH_PREFLIGHT_LIGHT_INIT_S", "540"
        )
    done = _install_init_watchdog()
    t0 = time.time()
    import jax

    try:
        ds = jax.devices()
    except Exception as e:
        sys.stderr.write(f"[preflight] init raised after {time.time()-t0:.0f}s: {e}\n")
        return 3
    done.set()
    if not light:
        import jax.numpy as jnp

        y = (jnp.ones((256, 256), jnp.bfloat16) @ jnp.ones((256, 256), jnp.bfloat16))
        jax.block_until_ready(y)
    info = {
        "platform": ds[0].platform,
        "device_kind": getattr(ds[0], "device_kind", "?"),
        "n_devices": len(ds),
        "init_s": round(time.time() - t0, 1),
        "light": light,
    }
    print(json.dumps(info), flush=True)
    return 0


def _write_atomic(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _resume_compatible(prev: dict, backend: str, model: str, n_train: int) -> bool:
    """Single source of truth for whether a saved partial can seed a run —
    used both by run_arms (which resumes it) and _try_arms (which reasons
    about shrink levels and file lifecycle); keep the criteria in one place
    so they cannot drift."""
    return (
        prev.get("backend") == backend
        and prev.get("model") == model
        and prev.get("n_train") == n_train
    )


def run_arms(out_path: str, force_cpu: bool, resume_path: str = "") -> int:
    """Run the dbs-off then dbs-on arm in THIS process (one backend init),
    writing per-epoch walls + instrumentation incrementally to out_path.

    ``resume_path``: a previous attempt's partial JSON; arms it already
    completed (same backend/model/n_train) are copied, not re-run — a retry
    after a mid-run runtime outage only pays for what was lost."""
    if force_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=4"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")  # beats the axon plugin
    done = _install_init_watchdog()
    import jax

    jax.devices()
    done.set()

    # Stall watchdog: a tunnel drop mid-run leaves PJRT blocked in C++ at 0%
    # CPU (observed: 45 min hung in the warm loop, round 3). The engine
    # heartbeats whenever the device answers; if neither the heartbeat nor
    # the incremental result file advances for BENCH_STALL_S, hard-exit so
    # the orchestrator retries instead of burning the budget.
    from dynamic_load_balance_distributeddnn_tpu.runtime.watchdog import (
        arm_stall_watchdog,
    )

    arm_stall_watchdog(
        out_path + ".hb",
        # default clears a cold whole-epoch XLA compile (~8-10 min observed)
        # with margin; a genuine hang then costs 15 min, not the whole budget
        float(os.environ.get("BENCH_STALL_S", 900)),
        extra_paths=(out_path,),
    )

    from dynamic_load_balance_distributeddnn_tpu.config import Config
    from dynamic_load_balance_distributeddnn_tpu.data import load_dataset
    from dynamic_load_balance_distributeddnn_tpu.faults import StaticStragglerInjector
    from dynamic_load_balance_distributeddnn_tpu.train import Trainer

    if force_cpu:
        n_train = int(os.environ.get("BENCH_CPU_NTRAIN", 2048))
        model, batch, bucket = "mnistnet", 512, 32
        dataset = "mnist"
    else:
        n_train = int(os.environ.get("BENCH_NTRAIN", 12800))
        model, batch, bucket = "densenet", 512, 32
        dataset = "cifar10"
    epochs = max(int(os.environ.get("BENCH_EPOCHS", 7)), 4)
    ws = int(os.environ.get("BENCH_WS", 4))
    # bf16 compute + f32 master weights: the MXU's native dtype (fp32 convs
    # forfeit most of the systolic array's throughput on v5e). Justified by
    # the MFU instrumentation — see artifacts/PRECISION.md; BENCH_PRECISION
    # flips the A/B.
    precision = os.environ.get("BENCH_PRECISION", "bfloat16")
    bundle = load_dataset(dataset, n_train=n_train, n_test=512)
    factors = [3.0] + [1.0] * (ws - 1)

    out = {
        "backend": "cpu_fallback" if force_cpu else "tpu",
        "n_train": n_train,
        "model": model,
        "world_size": ws,
        "straggler_factors": factors,
        "off": [],
        "on": [],
        "instr": {},
    }
    resume = {}
    if resume_path and os.path.exists(resume_path):
        try:
            with open(resume_path) as f:
                prev = json.load(f)
            if _resume_compatible(prev, out["backend"], model, n_train):
                resume = prev
        except Exception:
            pass
    _write_atomic(out_path, out)

    # epoch 0 calibrates (no injection), epoch 1 is the first injected epoch;
    # the off arm runs one epoch fewer (no rebalance to converge) so the two
    # arms' steady windows have comparable sample counts for the min
    for arm, dbs_on, n_ep in (("off", False, max(3, epochs - 1)), ("on", True, epochs)):
        if len(resume.get(arm, [])) >= n_ep:
            out[arm] = resume[arm][:n_ep]
            for k, v in resume.get("instr", {}).items():
                if k.startswith(arm + "_"):
                    out["instr"][k] = v
            # a resumed arm's timings are as old as the partial they came
            # from — carry its save stamp so measured_at_unix (and every TTL
            # built on it) bounds the TRUE measurement age, not assembly time.
            # The stamp is kept PER ARM as well, so a later strip of one arm
            # can recompute the file-level stamp from the survivors.
            src_ts = (resume.get("arm_saved_at") or {}).get(arm) or resume.get(
                "saved_at"
            )
            if src_ts:
                out.setdefault("arm_saved_at", {})[arm] = float(src_ts)
                out["saved_at"] = min(
                    float(out.get("saved_at") or src_ts), float(src_ts)
                )
            _write_atomic(out_path, out)
            sys.stderr.write(f"[bench] arm {arm} resumed from previous attempt\n")
            continue
        cfg = Config(
            debug=False,
            world_size=ws,
            batch_size=batch,
            learning_rate=0.01,
            epoch_size=n_ep,
            dataset=dataset,
            model=model,
            dynamic_batch_size=dbs_on,
            fault_tolerance=True,
            fault_mode="compute",
            bucket=bucket,
            precision=precision,
            # TPU (1 chip): NO warm ladder — both arms run the packed path,
            # whose window is the same [n, cap] shape through the same
            # fused_epoch_idx executable for every plan (tight _cap_packed),
            # so ONE compile — paid in excluded epoch 0 — serves both arms;
            # probe shapes self-warm untimed inside _probe_workers. The
            # elastic ladder warm_start used to trigger (16 DenseNet
            # compiles) burned 15-40 min of tunnel window for executables
            # this topology never times. CPU insurance (4-device mesh):
            # compute-mode injection forces the ELASTIC path there, where
            # fresh rebalanced shapes would compile inside timed walls — the
            # ladder warm stays.
            warm_start=dbs_on and force_cpu,
        )
        tr = Trainer(
            cfg,
            bundle=bundle,
            injector=StaticStragglerInjector(factors, mode="compute"),
            log_to_file=False,
        )
        for e in range(n_ep):
            wall = tr.run_epoch(e)["epoch_wall"]
            out[arm].append(round(wall, 4))
            _write_atomic(out_path, out)
        # stamp the freshly measured arm so later windows never mis-attribute
        # a resumed sibling's (older) file-level stamp to it
        out.setdefault("arm_saved_at", {})[arm] = time.time()
        for k in (
            "examples_per_s",
            "mfu_bf16_peak",
            "accuracy",
            # elastic-path host overhead (dispatch + put walls per step,
            # balance/timing.py HostOverheadMeter) — the superstep lever
            "host_overhead_per_step_s",
        ):
            if tr.recorder.data.get(k):
                out["instr"][f"{arm}_{k}"] = tr.recorder.data[k][-1]
        # corrected-injection reporting: the REALIZED injected:clean
        # device-compute profile (raw-wall-differenced calibration), printed
        # alongside the nominal factors so a result that ran past the
        # nominal ceiling is self-evident in the artifact
        if tr.recorder.meta.get("realized_injection_profile") is not None:
            out["instr"][f"{arm}_realized_injection_profile"] = tr.recorder.meta[
                "realized_injection_profile"
            ]
        # equal-injection-strength assertion (VERDICT r2 weak #2): the
        # in-step iteration cost must have been fixed-point calibrated on
        # the injection-free epoch, so every counted epoch runs at the
        # requested 3:1 strength
        out["instr"][f"{arm}_injection_calibrated"] = bool(
            getattr(tr, "_iter_cost_calibrated", False)
        )
        out["instr"][f"{arm}_iter_cost_us"] = (
            round(tr._iter_cost_s * 1e6, 3) if tr._iter_cost_s else None
        )
        # per-epoch MODELED PARALLEL wall: max over workers of the epoch's
        # per-worker compute seconds (probe-measured / cost-modeled,
        # dispatch-overhead-corrected). On a real ws-chip deployment the
        # epoch wall is this max — the frame the reference's multi-GPU
        # numbers live in — while epoch_wall above serializes all workers
        # through the one bench chip. Kept per epoch so _result_from can
        # apply the same steady-window slicing as the serialized walls.
        nt = tr.recorder.data.get("node_time") or []
        out["instr"][f"{arm}_parallel_walls_s"] = [
            round(float(max(v)), 4) if len(v) else None for v in nt
        ]
        if tr.recorder.meta.get("probe_dispatch_overhead_s") is not None:
            out["instr"][f"{arm}_probe_dispatch_overhead_s"] = tr.recorder.meta[
                "probe_dispatch_overhead_s"
            ]
        _write_atomic(out_path, out)

    if os.environ.get("BENCH_CLEAN", "1") == "1" and len(resume.get("clean", [])) < 2:
        # Clean-throughput leg: no straggler, fused whole-epoch SPMD scan —
        # the framework's peak single-pod-slice throughput/MFU (the A/B arms
        # run the elastic path under injection, which can't show this).
        cfg = Config(
            debug=False,
            world_size=ws,
            batch_size=batch,
            learning_rate=0.01,
            epoch_size=2,
            dataset=dataset,
            model=model,
            dynamic_batch_size=False,
            fault_tolerance=False,
            bucket=bucket,
            precision=precision,
        )
        tr = Trainer(cfg, bundle=bundle, log_to_file=False)
        for e in range(2):
            out.setdefault("clean", []).append(round(tr.run_epoch(e)["epoch_wall"], 4))
            _write_atomic(out_path, out)
        for k in ("examples_per_s", "mfu_bf16_peak"):
            if tr.recorder.data.get(k):
                out["instr"][f"clean_{k}"] = tr.recorder.data[k][-1]
        _write_atomic(out_path, out)
    elif resume.get("clean"):
        out["clean"] = resume["clean"]
        for k, v in resume.get("instr", {}).items():
            if k.startswith("clean_"):
                out["instr"][k] = v
        _write_atomic(out_path, out)

    if (
        force_cpu
        and os.environ.get("BENCH_DISPATCH_AB", "1") == "1"
        and "elastic_dispatch_ab" not in out["instr"]
    ):
        if resume.get("instr", {}).get("elastic_dispatch_ab"):
            out["instr"]["elastic_dispatch_ab"] = resume["instr"][
                "elastic_dispatch_ab"
            ]
        else:
            # Dispatch-overhead A/B (ISSUE 2 acceptance): the SAME elastic
            # plan driven through the legacy per-step loop vs the superstep
            # path, reporting per-step host overhead (dispatch + put walls)
            # as a field, not prose. Cheap on the CPU tier (2 short epochs
            # per leg); the arms above already run the superstep default.
            ab = {}
            for label, mode in (("per_step", "off"), ("superstep", "auto")):
                cfg = Config(
                    debug=False,
                    world_size=ws,
                    batch_size=batch,
                    learning_rate=0.01,
                    epoch_size=2,
                    dataset=dataset,
                    model=model,
                    dynamic_batch_size=True,
                    fault_tolerance=False,
                    bucket=bucket,
                    precision=precision,
                    superstep=mode,
                )
                tr = Trainer(cfg, bundle=bundle, log_to_file=False)
                for e in range(2):
                    tr.run_epoch(e)
                vals = tr.recorder.data.get("host_overhead_per_step_s") or []
                if vals:
                    # epoch 1: the uniform plan repeats epoch 0's shapes, so
                    # the wall holds no XLA compiles — steady-state overhead
                    ab[f"{label}_s"] = round(vals[-1], 6)
            if ab.get("per_step_s") and ab.get("superstep_s"):
                ab["reduction_x"] = round(ab["per_step_s"] / ab["superstep_s"], 3)
            out["instr"]["elastic_dispatch_ab"] = ab
        _write_atomic(out_path, out)

    if (
        force_cpu
        and os.environ.get("BENCH_AOT_AB", "1") == "1"
        and "aot_warm_ab" not in out["instr"]
    ):
        if resume.get("instr", {}).get("aot_warm_ab"):
            out["instr"]["aot_warm_ab"] = resume["instr"]["aot_warm_ab"]
        else:
            # Serial-vs-concurrent warm A/B (ISSUE 3 acceptance) in a
            # dedicated subprocess: it needs its own XLA flags (4-device CPU
            # mesh + per-program-serial codegen) and a disabled persistent
            # cache, neither of which can change after this process's
            # backend initialized.
            fd, ab_path = tempfile.mkstemp(suffix=".json")
            os.close(fd)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
            proc = None
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__), "--aot-ab",
                     "--out", ab_path],
                    capture_output=True,
                    text=True,
                    timeout=float(os.environ.get("BENCH_AOT_AB_TIMEOUT", 900)),
                    env=env,
                )
                with open(ab_path) as f:
                    ab = json.load(f)
                # the child writes incrementally: a crash mid-leg leaves a
                # syntactically-valid partial — only adopt a COMPLETE A/B
                # (speedup present) or an explicit error marker
                if proc.returncode == 0 and ("speedup_x" in ab or "error" in ab):
                    out["instr"]["aot_warm_ab"] = ab
                else:
                    sys.stderr.write(
                        f"[bench] aot_warm_ab incomplete (rc={proc.returncode}, "
                        f"keys={sorted(ab)}); dropped\n"
                    )
            except Exception as e:
                # a crash before the child's first write leaves an empty
                # file (JSONDecodeError lands here) — the child's stderr is
                # the only post-mortem, keep it
                sys.stderr.write(f"[bench] aot_warm_ab failed: {e}\n")
            finally:
                if proc is not None and proc.returncode != 0 and proc.stderr:
                    sys.stderr.write(proc.stderr[-800:] + "\n")
                try:
                    os.unlink(ab_path)
                except OSError:
                    pass
        _write_atomic(out_path, out)

    if (
        force_cpu
        and os.environ.get("BENCH_TRACE_AB", "1") == "1"
        and "trace_overhead_ab" not in out["instr"]
    ):
        if resume.get("instr", {}).get("trace_overhead_ab"):
            out["instr"]["trace_overhead_ab"] = resume["instr"]["trace_overhead_ab"]
        else:
            # graftscope overhead A/B (ISSUE 4 acceptance): the SAME elastic
            # DBS run with --trace off vs --trace on. The traced leg also
            # writes the Chrome-trace JSON, proves `graftscope summarize`
            # renders it, and reports the per-phase epoch attribution +
            # worst-epoch coverage (acceptance: >= 0.95, overhead < 1%).
            from dynamic_load_balance_distributeddnn_tpu.obs.trace import (
                attribution,
                configure as configure_tracer,
                load_trace,
            )

            ab = {
                # the tracer's true per-span cost is O(us) against O(s)
                # epochs; the measured delta is bounded by host jitter, so
                # a (small) negative overhead_pct reads as "below noise"
                "note": "min over steady epochs per leg; delta is jitter-bounded",
            }
            n_ab = 4  # epoch 0 pays compiles; steady window = epochs 1..n-1
            trace_path = out_path + ".trace.json"
            for label, mode in (("trace_off", "off"), ("trace_on", "on")):
                cfg = Config(
                    debug=False,
                    world_size=ws,
                    batch_size=batch,
                    learning_rate=0.01,
                    epoch_size=n_ab,
                    dataset=dataset,
                    model=model,
                    dynamic_batch_size=True,
                    fault_tolerance=False,
                    bucket=bucket,
                    precision=precision,
                    trace=mode,
                )
                tr = Trainer(cfg, bundle=bundle, log_to_file=False)
                walls = [tr.run_epoch(e)["epoch_wall"] for e in range(n_ab)]
                ab[f"{label}_wall_s"] = round(min(walls[1:]), 6)
                if mode == "on":
                    tr._trace.save(trace_path)
                    att = attribution(load_trace(trace_path))
                    ab["trace_events"] = len(tr._trace.events())
                    ab["attribution_coverage_min"] = att["coverage_min"]
                    # per-epoch attribution summary: phase seconds per epoch
                    ab["epoch_attribution"] = {
                        str(ep): info["phases"]
                        for ep, info in att["epochs"].items()
                    }
                    try:
                        from dynamic_load_balance_distributeddnn_tpu.obs.scope_cli import (
                            summarize,
                        )

                        ab["summarize_renders"] = bool(summarize(trace_path))
                    except Exception as e:
                        ab["summarize_renders"] = False
                        sys.stderr.write(f"[bench] graftscope summarize failed: {e}\n")
                # the tracer is process-global — the A/B arms above and any
                # later leg must run untraced
                configure_tracer("off")
            try:
                os.unlink(trace_path)
            except OSError:
                pass
            if ab.get("trace_off_wall_s") and ab.get("trace_on_wall_s"):
                ab["overhead_pct"] = round(
                    100.0
                    * (ab["trace_on_wall_s"] - ab["trace_off_wall_s"])
                    / ab["trace_off_wall_s"],
                    3,
                )
            out["instr"]["trace_overhead_ab"] = ab
        _write_atomic(out_path, out)

    if (
        force_cpu
        and os.environ.get("BENCH_OBS_AB", "1") == "1"
        and "obs_overhead_ab" not in out["instr"]
    ):
        if resume.get("instr", {}).get("obs_overhead_ab"):
            out["instr"]["obs_overhead_ab"] = resume["instr"]["obs_overhead_ab"]
        else:
            # Flight-recorder overhead A/B (ISSUE 15 acceptance): the SAME
            # elastic DBS run traced AND spooled (--trace ring +
            # --trace_spool, the crash-durable sink with its background
            # flusher) vs trace-off. The budget: enabled overhead stays
            # under a few percent of wall (the hot path adds ONE bounded-
            # deque append per event; serialization and I/O live on the
            # flusher thread). Also records spool bytes/step — the disk
            # price of crash durability.
            import shutil as _shutil

            from dynamic_load_balance_distributeddnn_tpu.obs.trace import (
                configure as configure_tracer,
            )

            spool_dir = tempfile.mkdtemp(prefix="bench_obs_ab_")
            ab = {
                "note": (
                    "min over steady epochs per leg; delta is jitter-"
                    "bounded, budget asserts <= 5%"
                ),
            }
            n_ab = 4
            try:
                for label, mode in (("off", "off"), ("spooled", "ring")):
                    cfg = Config(
                        debug=False,
                        world_size=ws,
                        batch_size=batch,
                        learning_rate=0.01,
                        epoch_size=n_ab,
                        dataset=dataset,
                        model=model,
                        dynamic_batch_size=True,
                        fault_tolerance=False,
                        bucket=bucket,
                        precision=precision,
                        trace=mode,
                        trace_spool=spool_dir if mode != "off" else "",
                        trace_spool_flush_s=0.1,
                    )
                    tr = Trainer(cfg, bundle=bundle, log_to_file=False)
                    walls = [
                        tr.run_epoch(e)["epoch_wall"] for e in range(n_ab)
                    ]
                    ab[f"{label}_wall_s"] = round(min(walls[1:]), 6)
                    if mode != "off":
                        ab["trace_events"] = tr._trace.event_count()
                        sp = tr.close_spool()
                        steps = n_ab * max(
                            -(-len(bundle.train_x) // batch), 1
                        )
                        if sp is not None:
                            ab["spool_bytes"] = int(sp.bytes_written)
                            ab["spool_bytes_per_step"] = round(
                                sp.bytes_written / steps, 1
                            )
                    # process-global tracer: later legs must run untraced
                    configure_tracer("off")
            finally:
                _shutil.rmtree(spool_dir, ignore_errors=True)
            if ab.get("off_wall_s") and ab.get("spooled_wall_s"):
                frac = (
                    ab["spooled_wall_s"] - ab["off_wall_s"]
                ) / ab["off_wall_s"]
                ab["overhead_pct"] = round(100.0 * frac, 3)
                ab["within_budget"] = bool(frac <= 0.05)
            out["instr"]["obs_overhead_ab"] = ab
        _write_atomic(out_path, out)

    if (
        force_cpu
        and os.environ.get("BENCH_WORKERS_AB", "1") == "1"
        and "compile_workers_ab" not in out["instr"]
    ):
        if resume.get("instr", {}).get("compile_workers_ab"):
            out["instr"]["compile_workers_ab"] = resume["instr"]["compile_workers_ab"]
        else:
            # Process-worker vs in-process-thread compile throughput A/B
            # (ISSUE 5 acceptance) in a dedicated subprocess: the thread leg
            # needs the persistent cache force-DISABLED and the process leg
            # repoints it at a fresh dir — neither can change in this
            # process after its backend initialized.
            fd, ab_path = tempfile.mkstemp(suffix=".json")
            os.close(fd)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
            proc = None
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__), "--workers-ab",
                     "--out", ab_path],
                    capture_output=True,
                    text=True,
                    timeout=float(os.environ.get("BENCH_WORKERS_AB_TIMEOUT", 1500)),
                    env=env,
                )
                with open(ab_path) as f:
                    ab = json.load(f)
                # the child writes incrementally: only adopt a COMPLETE A/B
                # (speedup present) or an explicit error marker
                if proc.returncode == 0 and ("speedup_x" in ab or "error" in ab):
                    out["instr"]["compile_workers_ab"] = ab
                else:
                    sys.stderr.write(
                        f"[bench] compile_workers_ab incomplete "
                        f"(rc={proc.returncode}, keys={sorted(ab)}); dropped\n"
                    )
            except Exception as e:
                sys.stderr.write(f"[bench] compile_workers_ab failed: {e}\n")
            finally:
                if proc is not None and proc.returncode != 0 and proc.stderr:
                    sys.stderr.write(proc.stderr[-800:] + "\n")
                try:
                    os.unlink(ab_path)
                except OSError:
                    pass
        _write_atomic(out_path, out)

    if (
        force_cpu
        and os.environ.get("BENCH_ELASTIC_AB", "1") == "1"
        and "elastic_recovery_ab" not in out["instr"]
    ):
        if resume.get("instr", {}).get("elastic_recovery_ab"):
            out["instr"]["elastic_recovery_ab"] = resume["instr"][
                "elastic_recovery_ab"
            ]
        else:
            # Elastic recovery A/B (ISSUE 6 acceptance): a chaos leg — the
            # PreemptionInjector kills 1 of ws workers mid-epoch 1, the
            # engine detects at a window boundary, re-solves over the
            # survivors, and keeps training — vs a fresh run STARTED at the
            # reduced world size. Reported: detection-to-resumed-training
            # time, the post-recovery steady epoch wall vs the fresh
            # reduced-fleet wall (ratio ~1 = no poisoned state, no lingering
            # tax), and the post-recovery foreground-compile sentinel (the
            # re-solve re-warms the new world size through the AOT service;
            # steady epochs must stay compile-silent).
            from dynamic_load_balance_distributeddnn_tpu.faults import (
                PreemptionEvent,
                PreemptionInjector,
            )

            ab = {}
            n_el = max(int(os.environ.get("BENCH_ELASTIC_AB_EPOCHS", 5)), 4)
            kill = ws - 1
            cfg = Config(
                debug=False,
                world_size=ws,
                batch_size=batch,
                learning_rate=0.01,
                epoch_size=n_el,
                dataset=dataset,
                model=model,
                dynamic_batch_size=True,
                fault_tolerance=False,
                bucket=bucket,
                precision=precision,
                elastic="on",
                warm_start=True,
                # several windows per epoch so the kill is detected
                # MID-epoch (the elastic path checks liveness at window
                # boundaries), not at the next epoch's boundary check
                stream_chunk_steps=1,
            )
            inj = PreemptionInjector(
                ws,
                [PreemptionEvent(worker=kill, down_at=1.4, rejoin_epoch=None)],
            )
            tr = Trainer(cfg, bundle=bundle, injector=inj, log_to_file=False)
            walls = [
                round(tr._run_epoch_elastic_world(e)["epoch_wall"], 4)
                for e in range(n_el)
            ]
            events = tr.recorder.meta.get("elastic_events") or []
            rec_ev = next((e for e in events if "lost" in e), None)
            if rec_ev is not None and tr.world_size == ws - 1:
                ab["killed_worker"] = kill
                ab["detected_epoch"] = rec_ev["epoch"]  # 1 = within the
                # epoch the kill landed in (detection-to-resume <= 1 epoch)
                ab["detect_to_resume_s"] = rec_ev["detect_to_resume_s"]
                ab["chaos_walls_s"] = walls
                # steady post-recovery window: the recovery epoch re-runs
                # (and pays the new world size's plan), the NEXT epochs are
                # the survivors' steady state
                post = walls[rec_ev["epoch"] + 1:]
                if post:
                    ab["post_recovery_wall_s"] = round(min(post), 4)
                xc = tr.recorder.data.get("xla_compiles") or []
                ab["post_recovery_fg_compiles"] = [
                    int(v) for v in xc[rec_ev["epoch"] + 1:]
                ]

                # the comparison leg keeps elastic ON (no injector): both
                # legs pay the standing elasticity cost (epoch snapshot,
                # health checks), so the ratio isolates recovery RESIDUE —
                # poisoned state or lingering tax — not the cost of
                # elasticity itself
                cfg2 = cfg.replace(world_size=ws - 1)
                tr2 = Trainer(cfg2, bundle=bundle, log_to_file=False)
                walls2 = [
                    round(tr2._run_epoch_elastic_world(e)["epoch_wall"], 4)
                    for e in range(n_el)
                ]
                ab["reduced_fresh_walls_s"] = walls2
                ab["reduced_fresh_wall_s"] = round(min(walls2[1:]), 4)
                if ab.get("post_recovery_wall_s"):
                    ab["post_vs_reduced_x"] = round(
                        ab["post_recovery_wall_s"] / ab["reduced_fresh_wall_s"],
                        3,
                    )
            else:
                ab["error"] = (
                    f"recovery did not complete (events={len(events)}, "
                    f"world_size={tr.world_size})"
                )
                sys.stderr.write(f"[bench] elastic_recovery_ab: {ab['error']}\n")
            out["instr"]["elastic_recovery_ab"] = ab
        _write_atomic(out_path, out)

    if (
        force_cpu
        and os.environ.get("BENCH_ELASTIC_MH_AB", "1") == "1"
        and "elastic_mh_recovery_ab" not in out["instr"]
    ):
        if resume.get("instr", {}).get("elastic_mh_recovery_ab"):
            out["instr"]["elastic_mh_recovery_ab"] = resume["instr"][
                "elastic_mh_recovery_ab"
            ]
        else:
            try:
                out["instr"]["elastic_mh_recovery_ab"] = (
                    _elastic_mh_recovery_ab()
                )
            except Exception as e:
                sys.stderr.write(f"[bench] elastic_mh_recovery_ab failed: {e}\n")
                out["instr"]["elastic_mh_recovery_ab"] = {"error": str(e)[:300]}
        _write_atomic(out_path, out)

    if (
        force_cpu
        and os.environ.get("BENCH_ONLINE_AB", "1") == "1"
        and "online_dbs_ab" not in out["instr"]
    ):
        if resume.get("instr", {}).get("online_dbs_ab"):
            out["instr"]["online_dbs_ab"] = resume["instr"]["online_dbs_ab"]
        else:
            # Online-DBS cadence A/B (ISSUE 11 acceptance): the SAME
            # time-varying compute-mode injection (sin schedule over a 5:1
            # straggler, period spanning epochs so the flanks cross epoch
            # boundaries) balanced at window cadence (--rebalance window:
            # the hysteresis controller switches plans MID-epoch) vs the
            # reference epoch cadence. The CONTENTION topology (all workers
            # one device, the reference's -gpu 0,0,0,0) makes the
            # controller's summed step-time model physically exact on this
            # serialized tier; per-step dispatch (superstep off) keeps the
            # whole bucket-8 rung ladder warm so NO plan — boundary or
            # mid-epoch — ever compiles inside a wall. Metric: MEAN wall
            # over the injected epochs (a min would erase exactly the
            # stale-plan transients the time-varying scenario exists to
            # measure); both arms run the identical deterministic schedule,
            # so the delta is the cadence.
            from dynamic_load_balance_distributeddnn_tpu.faults import (
                ScheduledStragglerInjector,
            )

            sched = os.environ.get("BENCH_ONLINE_SCHEDULE", "sin")
            period = float(os.environ.get("BENCH_ONLINE_PERIOD", 3.0))
            n_ep = max(int(os.environ.get("BENCH_ONLINE_EPOCHS", 7)), 4)
            online_factors = [5.0] + [1.0] * (ws - 1)
            ab = {
                "schedule": sched,
                "period_epochs": period,
                "nominal_injection_profile": online_factors,
            }
            for label, cadence in (("window", "window"), ("epoch", "epoch")):
                cfg = Config(
                    debug=False,
                    world_size=ws,
                    batch_size=128,
                    learning_rate=0.01,
                    epoch_size=n_ep,
                    dataset=dataset,
                    model=model,
                    dynamic_batch_size=True,
                    fault_tolerance=False,
                    fault_mode="compute",
                    bucket=8,
                    precision=precision,
                    warm_start=True,
                    stream_chunk_steps=2,
                    device=0,
                    packed="off",
                    superstep="off",
                    rebalance=cadence,
                )
                tr = Trainer(
                    cfg,
                    bundle=bundle,
                    injector=ScheduledStragglerInjector(
                        online_factors, mode="compute", schedule=sched,
                        period=period,
                    ),
                    log_to_file=False,
                )
                walls = [round(tr.run_epoch(e)["epoch_wall"], 4) for e in range(n_ep)]
                ab[f"{label}_walls_s"] = walls
                # epoch 0 calibrates injection-free; the injected epochs
                # 1..N-1 are the scenario — MEAN, not min (see above)
                ab[f"{label}_wall_s"] = round(
                    sum(walls[1:]) / max(len(walls) - 1, 1), 4
                )
                ab[f"{label}_injection_calibrated"] = bool(
                    getattr(tr, "_iter_cost_calibrated", False)
                )
                if tr.recorder.meta.get("realized_injection_profile") is not None:
                    ab[f"{label}_realized_injection_profile"] = tr.recorder.meta[
                        "realized_injection_profile"
                    ]
                if cadence == "window":
                    sw = tr.recorder.data.get("plan_switches") or []
                    ab["switches_per_epoch"] = [int(v) for v in sw]
                    ab["switch_count"] = int(sum(sw))
                    if tr._rebalance_ctl is not None:
                        # include_journal: the bench artifact doubles as a
                        # replay-lab corpus (balance/replaylab.load_corpus
                        # reads this section directly — ISSUE 19 harvest)
                        ab["controller"] = tr._rebalance_ctl.snapshot(
                            include_journal=True
                        )
                    ab["rebalance_events"] = tr.recorder.meta.get(
                        "rebalance_events", []
                    )
            if ab.get("window_wall_s") and ab.get("epoch_wall_s"):
                ab["speedup_x"] = round(
                    ab["epoch_wall_s"] / ab["window_wall_s"], 3
                )
            out["instr"]["online_dbs_ab"] = ab
        _write_atomic(out_path, out)

    if (
        force_cpu
        and os.environ.get("BENCH_CONTROLLER_SWEEP", "1") == "1"
        and "controller_sweep" not in out["instr"]
    ):
        if resume.get("instr", {}).get("controller_sweep"):
            out["instr"]["controller_sweep"] = resume["instr"][
                "controller_sweep"
            ]
        else:
            # Device-free controller-knob sweep (ISSUE 19): the replay
            # lab's small grid over the stock synthesized scenario library
            # (every ScheduledStragglerInjector schedule family), ranked by
            # geometric-mean speedup over the never-switch hold baseline.
            # Pure host-side numpy — records the best-found knob set
            # against the shipped defaults, plus the invariant-checker
            # verdict over every simulated journal.
            try:
                from dynamic_load_balance_distributeddnn_tpu.balance import (
                    replaylab,
                )

                t0 = time.time()
                report = replaylab.sweep(
                    replaylab.builtin_scenarios(4),
                    replaylab.knob_grid("small"),
                )
                out["instr"]["controller_sweep"] = {
                    "scenarios": report["scenarios"],
                    "candidates": report["candidates"],
                    "best": report["best"],
                    "default": report["default"],
                    "best_vs_default": report["best_vs_default"],
                    "invariant_violations": report["invariant_violations"],
                    "top5": [
                        {k: r[k] for k in ("knobs", "score", "switches")}
                        for r in report["results"][:5]
                    ],
                    "sweep_wall_s": round(time.time() - t0, 3),
                }
            except Exception as e:
                sys.stderr.write(f"[bench] controller_sweep failed: {e}\n")
                out["instr"]["controller_sweep"] = {"error": str(e)[:300]}
        _write_atomic(out_path, out)

    if (
        force_cpu
        and os.environ.get("BENCH_GRAD_COMM_AB", "1") == "1"
        and "grad_comm_ab" not in out["instr"]
    ):
        if resume.get("instr", {}).get("grad_comm_ab"):
            out["instr"]["grad_comm_ab"] = resume["instr"]["grad_comm_ab"]
        else:
            # Hierarchical-vs-flat gradient-collective A/B (ISSUE 12
            # acceptance) in a dedicated subprocess: the comm-bound leg
            # shapes the loopback to a DCN-class rate and spans two gloo
            # processes, which cannot share this process's already-
            # initialized in-process backend.
            fd, ab_path = tempfile.mkstemp(suffix=".json")
            os.close(fd)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
            proc = None
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--grad-comm-ab", "--out", ab_path],
                    capture_output=True,
                    text=True,
                    timeout=float(os.environ.get("BENCH_GRAD_COMM_AB_TIMEOUT", 900)),
                    env=env,
                )
                with open(ab_path) as f:
                    ab = json.load(f)
                if proc.returncode == 0 and ("speedup_x" in ab or "error" in ab):
                    out["instr"]["grad_comm_ab"] = ab
                else:
                    sys.stderr.write(
                        f"[bench] grad_comm_ab incomplete "
                        f"(rc={proc.returncode}, keys={sorted(ab)}); dropped\n"
                    )
            except Exception as e:
                sys.stderr.write(f"[bench] grad_comm_ab failed: {e}\n")
            finally:
                # the child unshapes lo in ITS finally, but an outer-timeout
                # SIGKILL skips finallys — never leave the fabric throttled
                # for the rest of the round
                _tc("qdisc", "del", "dev", "lo", "root")
                if proc is not None and proc.returncode != 0 and proc.stderr:
                    sys.stderr.write(proc.stderr[-800:] + "\n")
                try:
                    os.unlink(ab_path)
                except OSError:
                    pass
        _write_atomic(out_path, out)

    if (
        force_cpu
        and os.environ.get("BENCH_ZERO1_AB", "1") == "1"
        and "zero1_ab" not in out["instr"]
    ):
        if resume.get("instr", {}).get("zero1_ab"):
            out["instr"]["zero1_ab"] = resume["instr"]["zero1_ab"]
        else:
            # Sharded-vs-replicated weight-update A/B (ISSUE 13 acceptance)
            # in a dedicated subprocess: the leg wants a 4-device mesh (the
            # ~1/N shrink at world 4), which cannot share this process's
            # already-initialized backend.
            fd, ab_path = tempfile.mkstemp(suffix=".json")
            os.close(fd)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
            proc = None
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--zero1-ab", "--out", ab_path],
                    capture_output=True,
                    text=True,
                    timeout=float(os.environ.get("BENCH_ZERO1_AB_TIMEOUT", 600)),
                    env=env,
                )
                with open(ab_path) as f:
                    ab = json.load(f)
                if proc.returncode == 0 and "update_wall_ratio_x" in ab:
                    out["instr"]["zero1_ab"] = ab
                else:
                    sys.stderr.write(
                        f"[bench] zero1_ab incomplete "
                        f"(rc={proc.returncode}, keys={sorted(ab)}); dropped\n"
                    )
            except Exception as e:
                sys.stderr.write(f"[bench] zero1_ab failed: {e}\n")
            finally:
                if proc is not None and proc.returncode != 0 and proc.stderr:
                    sys.stderr.write(proc.stderr[-800:] + "\n")
                try:
                    os.unlink(ab_path)
                except OSError:
                    pass
        _write_atomic(out_path, out)

    if (
        force_cpu
        and os.environ.get("BENCH_MULTISTREAM_AB", "1") == "1"
        and "multistream_ab" not in out["instr"]
    ):
        if resume.get("instr", {}).get("multistream_ab"):
            out["instr"]["multistream_ab"] = resume["instr"]["multistream_ab"]
        else:
            # K-small-jobs sequential vs multiplexed A/B (ISSUE 18
            # acceptance) in a dedicated subprocess: the legs want a fresh
            # 8-device mesh and their own compile lineage.
            fd, ab_path = tempfile.mkstemp(suffix=".json")
            os.close(fd)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
            proc = None
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--multistream-ab", "--out", ab_path],
                    capture_output=True,
                    text=True,
                    timeout=float(
                        os.environ.get("BENCH_MULTISTREAM_AB_TIMEOUT", 900)
                    ),
                    env=env,
                )
                with open(ab_path) as f:
                    ab = json.load(f)
                if proc.returncode == 0 and "speedup_x" in ab:
                    out["instr"]["multistream_ab"] = ab
                else:
                    sys.stderr.write(
                        f"[bench] multistream_ab incomplete "
                        f"(rc={proc.returncode}, keys={sorted(ab)}); dropped\n"
                    )
            except Exception as e:
                sys.stderr.write(f"[bench] multistream_ab failed: {e}\n")
            finally:
                if proc is not None and proc.returncode != 0 and proc.stderr:
                    sys.stderr.write(proc.stderr[-800:] + "\n")
                try:
                    os.unlink(ab_path)
                except OSError:
                    pass
        _write_atomic(out_path, out)
    return 0


def run_aot_ab(out_path: str) -> int:
    """Serial execute-to-compile vs concurrent AOT warm-start A/B (the
    ISSUE-3 acceptance field ``aot_warm_ab``). Runs in its own subprocess:
    the parent pins a 4-device CPU mesh (both legs see identical XLA
    flags), and the persistent compilation cache is disabled so BOTH legs
    pay real backend compiles — equal compile counts is the fairness
    condition.

    Leg A (``--aot_warm off``): the legacy warm — compile by executing dummy
    steps, serially, with per-rung device_put traffic. Leg B: the AOT
    service — lower(abstract).compile() jobs on the thread pool. Same
    config, same ladder, fresh StepLibrary per leg (no in-memory reuse).

    What the delta measures: the execute-to-compile tax — the dummy
    EXECUTIONS (a ResNet forward+backward at warm rungs costs ~2x the
    compile itself on this tier), the per-rung host→device transfers, and
    GIL-serial tracing that the AOT leg pipelines under backend compiles.
    Concurrent conv-program compiles contend ~fully on this 2-core tier
    (measured: jobs overlap 2x but stretch 2x), so the CPU-tier speedup is
    a LOWER bound for backends/hosts whose compilers scale across cores."""
    done = _install_init_watchdog()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.devices()
    done.set()
    # authoritative regardless of inherited env: both legs recompile for real
    jax.config.update("jax_enable_compilation_cache", False)

    from dynamic_load_balance_distributeddnn_tpu.analysis.guards import (
        compile_budget,
    )
    from dynamic_load_balance_distributeddnn_tpu.config import Config
    from dynamic_load_balance_distributeddnn_tpu.data import load_dataset
    from dynamic_load_balance_distributeddnn_tpu.train import Trainer

    n_train = int(os.environ.get("BENCH_AOT_AB_NTRAIN", 1024))
    bundle = load_dataset("cifar10", n_train=n_train, n_test=256)
    out = {}
    for label, aot in (("serial_execute", False), ("concurrent_aot", True)):
        # ResNet-18 on the CIFAR shape: the model family where warm-rung
        # dummy executions genuinely dominate (the bench's DenseNet ladder
        # burned 15-40 min of tunnel window exactly this way). ws=2 and
        # capacity_factor=1.0 keep the ladder at 2 rungs (64/128) so the AB
        # finishes in ~2 min on the CPU tier.
        cfg = Config(
            debug=False,
            world_size=2,
            batch_size=256,
            learning_rate=0.01,
            epoch_size=1,
            dataset="cifar10",
            model="resnet18",
            dynamic_batch_size=True,
            bucket=64,
            capacity_factor=1.0,
            warm_start=True,
            aot_warm=aot,
        )
        tr = Trainer(cfg, bundle=bundle, log_to_file=False)
        t0 = time.perf_counter()
        with compile_budget(label=label, include_background=True) as budget:
            tr._maybe_warm()
            if tr._aot is not None:
                failures = tr._aot.wait()
                if failures:
                    # top-level marker too: the parent only adopts a file
                    # carrying speedup_x or an explicit error
                    out["error"] = f"{label}: {len(failures)} compile jobs failed"
                    out[label] = {"error": out["error"]}
                    break
        out[label] = {
            "warm_wall_s": round(time.perf_counter() - t0, 3),
            "compile_events": budget.count,
        }
        if tr._aot is not None:
            st = tr._aot.stats()
            out[label]["jobs"] = int(st["compiled"])
            out[label]["pool"] = tr._aot._workers
        _write_atomic(out_path, out)
    ser = out.get("serial_execute", {}).get("warm_wall_s")
    con = out.get("concurrent_aot", {}).get("warm_wall_s")
    if ser and con:
        out["speedup_x"] = round(ser / con, 3)
        # the fairness condition: both legs compiled the same program set
        out["equal_compile_counts"] = (
            abs(
                out["serial_execute"]["compile_events"]
                - out["concurrent_aot"]["compile_events"]
            )
            <= 0.1 * out["serial_execute"]["compile_events"] + 2
        )
    _write_atomic(out_path, out)
    return 0


def run_workers_ab(out_path: str) -> int:
    """Process-worker vs in-process-thread compile throughput A/B (the
    ISSUE-5 ``compile_workers_ab`` field). The SAME eight mesh-placed
    resnet18 worker-step programs (4 devices x 2 ladder rungs, the engine's
    own AOT lowerables) are submitted through the AOTCompileService twice:
    ``backend="thread"`` then ``backend="process"`` — equal compile counts
    by construction, identical program set.

    Fairness: the thread leg runs FIRST with the persistent compilation
    cache force-disabled, so every job is a real backend compile. The
    process leg then points the cache at a FRESH directory (the worker
    channel; ``ensure_persistent_cache`` resets jax's memoized cache-used
    decision) so its workers also compile every program for real — the
    parent's replays landing as cache hits is the mechanism under test, not
    a shortcut, and ``replay_cache_hits`` records it. A fresh Trainer per
    leg keeps jit tracing caches from subsidizing leg 2. Worker spawn +
    jax import (reported as ``worker_startup_s``) happens BEFORE the timed
    window — in production it overlaps the run's own warm-up.

    Interpretation: with compile work core-bound on this 2-core CI tier,
    both legs saturate the same cores and the wall ratio hovers near 1x —
    ``cores`` rides along so the ratio is read against the hardware. The
    worker pool's scaling headroom (each worker owns an emitter + GIL)
    shows when cores exceed the concurrent-program count; ROADMAP records
    the many-core sizing follow-up."""
    done = _install_init_watchdog()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.devices()
    done.set()
    # thread leg must pay real compiles: the bench-wide pinned cache (and
    # any entries a previous round left in it) is off the table
    jax.config.update("jax_enable_compilation_cache", False)

    from dynamic_load_balance_distributeddnn_tpu.config import Config
    from dynamic_load_balance_distributeddnn_tpu.data import load_dataset
    from dynamic_load_balance_distributeddnn_tpu.train import Trainer

    rungs = (64, 128)
    n_workers = int(os.environ.get("BENCH_WORKERS_AB_WORKERS", 4))
    bundle = load_dataset("cifar10", n_train=1024, n_test=256)
    out = {
        "model": "resnet18",
        "rungs": list(rungs),
        "workers": n_workers,
        "cores": os.cpu_count(),
        "note": "equal compile counts (identical program set per leg); "
        "thread leg first, persistent cache disabled for it; wall ratio is "
        "core-bound on few-core hosts",
    }
    replay_hits = []
    from jax._src import monitoring

    monitoring.register_event_listener(
        lambda name, **kw: replay_hits.append(name)
        if name == "/jax/compilation_cache/cache_hits"
        else None
    )

    def leg(backend):
        cfg = Config(
            debug=False,
            world_size=4,
            batch_size=256,
            learning_rate=0.01,
            epoch_size=1,
            dataset="cifar10",
            model="resnet18",
            dynamic_batch_size=True,
            bucket=64,
            capacity_factor=2.0,
            warm_start=False,
            aot_warm=True,
            aot_backend=backend,
            aot_workers=n_workers,
        )
        tr = Trainer(cfg, bundle=bundle, log_to_file=False)
        svc = tr._aot
        res = {}
        if backend == "process":
            pool = svc._ensure_worker_pool()
            if pool is None:
                return None, {"error": "worker pool unavailable"}
            pool.wait_ready(
                timeout=float(os.environ.get("BENCH_WORKERS_AB_SPAWN_S", 300)),
                all_workers=True,
            )
            res["worker_startup_s"] = round(pool.startup_s or 0.0, 3)
        t0 = time.perf_counter()
        jobs = []
        for d in tr.topology.used_device_indices:
            for b in rungs:
                jobs += tr._aot_submit_worker_steps(
                    d, b, (), want_acc=False, want_plain=True
                )
        failures = svc.wait()
        res["wall_s"] = round(time.perf_counter() - t0, 3)
        st = svc.stats()
        res["jobs"] = len(jobs)
        res["compiled"] = int(st["compiled"])
        if failures:
            res["error"] = f"{len(failures)} compile jobs failed"
        if backend == "process":
            res["worker_compiled"] = int(st["worker_compiled"])
            res["worker_fallback"] = int(st["worker_fallback"])
        svc.close()
        return res if "error" not in res else None, res

    thread_res, raw = leg("thread")
    out["thread"] = raw
    _write_atomic(out_path, out)
    if thread_res is None:
        out["error"] = raw.get("error", "thread leg failed")
        _write_atomic(out_path, out)
        return 1

    # the worker channel: a fresh cache dir (never the bench-wide pinned one
    # — its prior-round entries would turn worker compiles into lookups and
    # fake the throughput)
    cache_dir = tempfile.mkdtemp(prefix="bench_workers_ab_cache_")
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_enable_compilation_cache", True)
    replay_hits.clear()
    proc_res, raw = leg("process")
    raw["replay_cache_hits"] = len(replay_hits)
    out["process"] = raw
    if proc_res is None:
        out["error"] = raw.get("error", "process leg failed")
        _write_atomic(out_path, out)
        return 1
    out["equal_compile_counts"] = thread_res["compiled"] == proc_res["compiled"]
    if proc_res["wall_s"] > 0:
        out["speedup_x"] = round(thread_res["wall_s"] / proc_res["wall_s"], 3)
        out["thread_programs_per_min"] = round(
            60.0 * thread_res["compiled"] / thread_res["wall_s"], 2
        )
        out["process_programs_per_min"] = round(
            60.0 * proc_res["compiled"] / proc_res["wall_s"], 2
        )
    _write_atomic(out_path, out)
    return 0


# --------------------------------------------------------------- orchestrator


def _tc(*args) -> bool:
    """Best-effort traffic-control invocation (loopback shaping for the
    grad_comm A/B). Returns success; never raises."""
    try:
        return (
            subprocess.run(
                ["tc", *args], capture_output=True, text=True, timeout=10
            ).returncode
            == 0
        )
    except Exception:
        return False


def _resnet18_grad_sizes() -> list:
    """resnet18-scale gradient tree: ~11.0M f32 elements (44 MB) over 19
    conv/dense/bn-shaped leaves — the bytes profile of the repo's standard
    bench model, without paying its CPU model-compile wall inside a comm
    microbench. Shared by the 2-host and 3-tier grad_comm workers."""
    return (
        [64 * 3 * 7 * 7]
        + [64 * 64 * 3 * 3] * 4
        + [64 * 128 * 3 * 3, 128 * 128 * 3 * 3, 128 * 128 * 3 * 3,
           128 * 128 * 3 * 3]
        + [128 * 256 * 3 * 3, 256 * 256 * 3 * 3, 256 * 256 * 3 * 3,
           256 * 256 * 3 * 3]
        + [256 * 512 * 3 * 3, 512 * 512 * 3 * 3, 512 * 512 * 3 * 3,
           512 * 512 * 3 * 3]
        + [512 * 10, 512, 512]
    )


def _run_grad_comm_tier3_worker(proc_id: int, num_procs: int, port: int) -> int:
    """One process of the 3-tier grad_comm leg (ISSUE 17): two gloo
    processes x 4 in-process CPU devices = a ``(dcn 2, host 2, device 2)``
    fabric where ONLY the dcn hop rides the (shaped) loopback — the host and
    device levels are in-process memory, the fast-link classes of a real
    pod. Times three arms on the same resnet18-scale tree:

    * flat — per-leaf f32 psum over all three axes;
    * hier2 — the PR-12 hardwired two-level spine (``hier_tree_allreduce``,
      hosts=2 x 4 devices, its default int8 wire): ONE compressed hop, but
      the codec is fixed regardless of how slow the link actually is;
    * tree3 — ``tree_allreduce`` over the 3-level tree with the per-hop
      codec the ISSUE-17 cost model (``choose_wires``) picks from the
      actual link classes: the shaped dcn rate vs memory-class in-process
      rates. On a DCN-bound fabric it compresses the slow hop harder
      (int4) and keeps the fast hops exact — fewer bytes on the ONLY link
      that matters, so the wall undercuts both fixed arms.

    Honesty note for this tier: under the gloo CPU backend even the
    in-process hops ride loopback sockets, so the shaped rate throttles
    every level, not just dcn — the fp32 phases dominate all three arms
    and compress the margins. The structural claim (per-hop codec wall <=
    flat and <= fixed-int8 2-level) still measures cleanly; a real pod's
    in-host links would only widen it. The tree is a ~3.9M-element slice
    of the resnet18 profile (the three largest conv leaves dropped) so
    both shaped rates finish inside the worker timeout."""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=num_procs,
        process_id=proc_id,
    )
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dynamic_load_balance_distributeddnn_tpu.parallel import wire as wirefmt
    from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
        hier_mesh,
        shard_map,
        tree_mesh,
    )

    devs = jax.devices()
    assert num_procs == 2 and len(devs) == 8, (num_procs, len(devs))
    names3, sizes3 = ("dcn", "host", "device"), (2, 2, 2)
    mesh3 = tree_mesh(devs, names3, sizes3)
    mesh2 = hier_mesh(devs, 2)  # the PR-12 factorization of the same fleet

    sizes = [s for s in _resnet18_grad_sizes() if s != 512 * 512 * 3 * 3]
    n_elems = int(sum(sizes))
    rng = np.random.RandomState(7 + proc_id)
    local = [rng.standard_normal((4, s)).astype(np.float32) for s in sizes]

    # per-hop codec from the shipped cost model at the ACTUAL link classes:
    # the shaped loopback rate on the dcn hop, memory-class rates on the
    # in-process hops — compression lands on the slow link only
    dcn_rate = float(os.environ.get("BENCH_GRAD_COMM_RATE_MBIT", 200)) * 1e6 / 8
    mem_rate = 1e10
    wires3 = wirefmt.choose_wires(sizes3, [dcn_rate, mem_rate, mem_rate])

    reps = int(os.environ.get("BENCH_GRAD_COMM_TIER3_REPS", 2))

    def timed(mesh, body):
        bx = tuple(mesh.axis_names)
        sh = NamedSharding(mesh, P(bx))
        stacked = [
            jax.make_array_from_process_local_data(sh, a) for a in local
        ]
        fn = jax.jit(
            shard_map(
                body, mesh=mesh,
                in_specs=tuple(P(bx) for _ in stacked),
                out_specs=tuple(P() for _ in stacked),
                check_vma=False,
            )
        )
        jax.block_until_ready(fn(*stacked))  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*stacked))
            best = min(best, time.perf_counter() - t0)
        return best

    ax3 = tuple(mesh3.axis_names)
    h_ax2, d_ax2 = mesh2.axis_names

    def flat_body(*st):
        return tuple(jax.lax.psum(jnp.sum(g, axis=0), ax3) for g in st)

    def hier2_body(*st):
        out, _res = wirefmt.hier_tree_allreduce(
            [jnp.sum(g, axis=0) for g in st],
            jax.random.PRNGKey(3), h_ax2, d_ax2, 2, 4, "int8",
        )
        return tuple(out)

    def tree3_body(*st):
        out, _res = wirefmt.tree_allreduce(
            [jnp.sum(g, axis=0) for g in st],
            jax.random.PRNGKey(3), names3, sizes3, wires3,
        )
        return tuple(out)

    res = {
        "flat_wall_s": round(timed(mesh3, flat_body), 4),
        "hier2_int8_wall_s": round(timed(mesh2, hier2_body), 4),
        "tree3_wall_s": round(timed(mesh3, tree3_body), 4),
        "tree3_wires": list(wires3),
        "tree_elems": n_elems,
    }
    # per-hop bytes-on-wire, per device per combine — the engine's
    # _modeled_comm_step_s accounting (innermost fp32 RS+AG, middle
    # compressed-up + fp32 gather-down, top compressed all-reduce), so the
    # bench's detail matches what the controller's comm term is fed
    w3 = wirefmt.tree_hop_widths(n_elems, sizes3)
    w2 = wirefmt.tree_hop_widths(n_elems, (2, 4))
    res["tree3_hop_bytes"] = {
        "dcn": w3[0] * wirefmt.wire_payload_bytes(wires3[0], sizes3[0]),
        "host": w3[1] * (wirefmt.wire_payload_bytes(wires3[1], sizes3[1]) + 4),
        "device": 2 * n_elems * 4,
    }
    res["hier2_hop_bytes"] = {
        "dcn": w2[0] * wirefmt.wire_payload_bytes("int8", 2),
        "device": 2 * n_elems * 4,
    }
    res["flat_hop_bytes"] = {"all_links": 2 * n_elems * 4}
    if proc_id == 0:
        print("RESULT " + json.dumps(res), flush=True)
    return 0


def run_grad_comm_worker(proc_id: int, num_procs: int, port: int) -> int:
    """One host of the grad_comm A/B fabric: a single-device process on the
    gloo CPU collectives backend — every cross-process byte rides the
    (shaped) loopback, which IS the DCN under test. Times the SHIPPED
    combine structures on a resnet18-scale (11.2M element) gradient tree:

    * flat — the fused body's per-leaf f32 psum over the whole mesh;
    * hier — parallel/wire.py ``hier_tree_allreduce`` (the exact spine
      StepLibrary._hier_combine dispatches): ravel once, in-host
      reduce-scatter, ONE compressed cross-host hop, in-host all-gather —
      at each wire format.

    One chip per host is the DCN-pure profile (v5e-1-class hosts): the
    in-host phases are identity, so the measured delta isolates the
    compressed hop; on multi-chip hosts the reduce-scatter additionally
    divides the hop payload by D (bytes recorded per arm by the engine's
    comm_bytes series)."""
    if os.environ.get("BENCH_GRAD_COMM_TIER3") == "1":
        return _run_grad_comm_tier3_worker(proc_id, num_procs, port)
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=num_procs,
        process_id=proc_id,
    )
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dynamic_load_balance_distributeddnn_tpu.parallel import wire as wirefmt
    from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
        hier_mesh,
        shard_map,
    )
    from dynamic_load_balance_distributeddnn_tpu.parallel.topology import (
        factor_hosts,
    )

    devs = jax.devices()
    hosts = factor_hosts(devs)
    assert hosts == num_procs, (hosts, num_procs)
    mesh = hier_mesh(devs, hosts)
    h_ax, d_ax = mesh.axis_names
    n_d = int(mesh.shape[d_ax])
    bx = (h_ax, d_ax)

    sizes = _resnet18_grad_sizes()
    rng = np.random.RandomState(7)
    sh = NamedSharding(mesh, P(bx))
    stacked = [
        jax.make_array_from_process_local_data(
            sh, rng.standard_normal((1, s)).astype(np.float32)
        )
        for s in sizes
    ]
    n_elems = int(sum(sizes))

    def flat_body(*st):
        # the shipped flat combine's collective pattern: per-leaf f32 psum
        return tuple(
            jax.lax.psum(jnp.sum(g, axis=0), (h_ax, d_ax)) for g in st
        )

    def hier_body_of(wire):
        def hier_body(*st):
            local = [jnp.sum(g, axis=0) for g in st]
            out, _res = wirefmt.hier_tree_allreduce(
                local, jax.random.PRNGKey(3), h_ax, d_ax, hosts, n_d, wire
            )
            return tuple(out)

        return hier_body

    in_sp = tuple(P(bx) for _ in stacked)
    out_sp = tuple(P() for _ in stacked)
    reps = int(os.environ.get("BENCH_GRAD_COMM_REPS", 4))

    def timed(body):
        fn = jax.jit(
            shard_map(
                body, mesh=mesh, in_specs=in_sp, out_specs=out_sp,
                check_vma=False,
            )
        )
        jax.block_until_ready(fn(*stacked))  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*stacked))
            best = min(best, time.perf_counter() - t0)
        return best

    res = {"flat_wall_s": round(timed(flat_body), 4)}
    for wire in ("fp32", "int8", "int4"):
        res[f"hier_{wire}_wall_s"] = round(timed(hier_body_of(wire)), 4)
    res["tree_elems"] = n_elems
    res["tree_leaves"] = len(sizes)
    if proc_id == 0:
        print("RESULT " + json.dumps(res), flush=True)
    return 0


def _elastic_mh_recovery_ab() -> dict:
    """Multi-host elasticity chaos leg (ISSUE 14 acceptance field
    ``elastic_mh_recovery_ab``): a REAL two-process rendezvous run
    (tests/_mh_worker.py, DBS_MH_RDZV mode — 2 procs × 2 virtual CPU
    devices, ws=4) where the parent SIGKILLs one peer at its epoch-1
    marker. The survivor detects the loss (collective-failure attribution
    + stale beacon), re-rendezvouses over the survivor set, restores the
    flushed checkpoint onto the reduced mesh and finishes the run.
    Reported: detection-to-resumed-training wall for the REAL process kill,
    the post-recovery foreground-compile sentinel, and the survivor's
    end-of-run fleet shape."""
    import socket

    worker = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tests", "_mh_worker.py"
    )
    if not os.path.exists(worker):
        return {"error": "tests/_mh_worker.py not found"}
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    tmp = tempfile.mkdtemp(prefix="bench_mh_ab_")
    hb = os.path.join(tmp, "hb")
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    repo = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.update(
        DBS_MH_RDZV="1",
        DBS_PEER_HB_DIR=hb,
        DBS_MH_CKPT=os.path.join(tmp, "ck"),
        DBS_MH_EPOCHS=os.environ.get("BENCH_MH_AB_EPOCHS", "3"),
        DBS_MH_WS="4",
        DBS_PEER_HB_PERIOD_S="0.2",
        DBS_PEER_HB_STALE_S="2.0",
        DBS_RDZV_TIMEOUT_S="60",
    )
    timeout_s = float(os.environ.get("BENCH_MH_AB_TIMEOUT", 420))
    logs = [os.path.join(tmp, f"p{i}.log") for i in range(2)]
    procs = []
    try:
        for i in range(2):
            with open(logs[i], "w") as lf:
                procs.append(
                    subprocess.Popen(
                        [sys.executable, worker, str(i), "2", str(port)],
                        stdout=lf,
                        stderr=subprocess.STDOUT,
                        env=env,
                        cwd=repo,
                    )
                )
        marker = os.path.join(hb, "epoch1_p1.marker")
        deadline = time.time() + timeout_s
        while time.time() < deadline and not os.path.exists(marker):
            if any(p.poll() is not None for p in procs):
                break
            time.sleep(0.1)
        if not os.path.exists(marker):
            return {"error": "fleet never reached epoch 1"}
        procs[1].send_signal(signal.SIGKILL)
        t_kill = time.time()
        rc0 = procs[0].wait(timeout=timeout_s)
        wall_after_kill = time.time() - t_kill
        out0 = open(logs[0]).read()
        if rc0 != 0:
            return {
                "error": f"survivor rc={rc0}",
                "tail": out0[-500:],
            }
        lines = [ln for ln in out0.splitlines() if ln.startswith("RESULT ")]
        if not lines:
            return {"error": "survivor produced no RESULT line"}
        r = json.loads(lines[-1][len("RESULT "):])
        ev = next(
            (e for e in r.get("elastic_events", []) if "lost" in e), None
        )
        if ev is None or r.get("n_proc") != 1:
            return {
                "error": "no shrink rendezvous recorded",
                "events": r.get("elastic_events", []),
            }
        ab = {
            "killed_proc": 1,
            "detect_to_resume_s": ev["detect_to_resume_s"],
            "rdzv_gen": ev["rdzv_gen"],
            "restored_from": ev["restored_from"],
            "world_size_after": r["world_size"],
            "survivor_wall_after_kill_s": round(wall_after_kill, 2),
            "post_recovery_fg_compiles": [
                int(v) for v in r.get("xla_compiles", [])[ev["epoch"] + 1:]
            ],
            "losses_after_recovery": [
                round(float(v), 6) for v in r.get("losses", [])[ev["epoch"]:]
            ],
        }
        return ab
    finally:
        for p in procs:
            try:
                p.kill()
                p.wait(timeout=30)
            except (OSError, ProcessLookupError, subprocess.TimeoutExpired):
                pass
        import shutil as _sh

        _sh.rmtree(tmp, ignore_errors=True)


def _grad_comm_world(num_procs: int, env_extra: dict, timeout_s: float):
    """Spawn a ``num_procs``-process gloo grad_comm worker world on a fresh
    port and parse rank 0's ``RESULT`` line. Returns ``(result_dict, None)``
    or ``(None, error_string)``; hung workers are killed so a dead world
    never pins the port or contends with later timed arms."""
    import socket

    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--grad-comm-worker", str(i), str(num_procs), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for i in range(num_procs)
    ]
    try:
        outs = [p.communicate(timeout=timeout_s) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    line = next(
        (
            ln
            for o, _e in outs
            for ln in o.splitlines()
            if ln.startswith("RESULT ")
        ),
        None,
    )
    if line is None or any(p.returncode != 0 for p in procs):
        sys.stderr.write(outs[0][1][-800:] + "\n")
        return None, (
            f"worker rcs {[p.returncode for p in procs]}; no RESULT line"
        )
    return json.loads(line[len("RESULT "):]), None


def run_grad_comm_ab(out_path: str) -> int:
    """Hierarchical-vs-flat gradient-collective A/B (ISSUE 12 acceptance
    field ``grad_comm_ab``), in a dedicated subprocess tree.

    Leg 1 (parity, in-process 8-device 2x4 mesh): integer-valued gradients
    sum EXACTLY in f32 under any grouping, so the fp32-wire hier spine must
    be bit-for-bit one flat psum — ``parity_fp32_bitwise``.

    Leg 2 (the comm-bound wall): the loopback is shaped to a DCN-class
    bandwidth (tbf, BENCH_GRAD_COMM_RATE_MBIT, default 200) and two
    single-device gloo processes — every cross-host byte on the shaped
    link, the profile where the flat combine IS the epoch wall — time the
    shipped flat and hier combines on a resnet18-scale tree.
    ``speedup_x`` = flat / hier at the default int8 wire. The shaping is
    removed in a finally (and pre-cleaned at entry, so a killed previous
    run cannot leave the fabric throttled — the run_arms caller also
    best-effort-unshapes after this subprocess exits, covering a SIGKILL
    that skips the finally). No tc available -> the leg is skipped with an
    explicit marker (parity still reported).

    Leg 3 (ISSUE 17, the 3-tier wall): a (dcn, host, device) = (2, 2, 2)
    fabric — two gloo processes x 4 in-process devices, only the dcn hop on
    the shaped loopback — timed at TWO DCN rates
    (BENCH_GRAD_COMM_TIER3_RATES, default 200,60 mbit), proving the
    per-hop codec chosen by the cost model puts the N-level wall at or
    under both the flat and the fixed-int8 two-level arms, with per-hop
    bytes-on-wire recorded per arm."""
    done = _install_init_watchdog()
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dynamic_load_balance_distributeddnn_tpu.parallel import wire as wirefmt
    from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
        hier_mesh,
        shard_map,
    )

    ab = {}
    done.set()

    # ---- leg 1: bitwise fp32 parity on the in-process 2x4 mesh ----
    mesh = hier_mesh(jax.devices(), 2)
    h_ax, d_ax = mesh.axis_names
    bx = (h_ax, d_ax)
    n = len(jax.devices())
    vals = np.random.RandomState(0).randint(-64, 64, size=(n, 4099)).astype(
        np.float32
    )
    x = jax.device_put(vals, NamedSharding(mesh, P(bx)))

    def hier_body(v):
        out, _res = wirefmt.hier_tree_allreduce(
            [v[0]], jax.random.PRNGKey(0), h_ax, d_ax,
            int(mesh.shape[h_ax]), int(mesh.shape[d_ax]), "fp32",
        )
        return out[0][None]

    def flat_body(v):
        return jax.lax.psum(v, (h_ax, d_ax))

    hier_fn = jax.jit(
        shard_map(hier_body, mesh=mesh, in_specs=P(bx), out_specs=P(bx),
                  check_vma=False)
    )
    flat_fn = jax.jit(
        shard_map(flat_body, mesh=mesh, in_specs=P(bx), out_specs=P(bx),
                  check_vma=False)
    )
    out_h = np.asarray(hier_fn(x))[0]
    out_f = np.asarray(flat_fn(x))[0]
    ab["parity_fp32_bitwise"] = bool(
        np.array_equal(out_h, out_f) and np.array_equal(out_h, vals.sum(axis=0))
    )
    _write_atomic(out_path, ab)

    # ---- leg 2: shaped-DCN wall A/B across two gloo processes ----
    # DCN-class ceiling for the shaped loopback. 200 mbit keeps the leg
    # firmly bandwidth-bound: at 400+ the per-op fixed costs (gloo
    # chunking, the monolithic raveled transfer vs the flat arm's
    # pipelined per-leaf ops) eat most of the compressed wire's margin
    rate = int(os.environ.get("BENCH_GRAD_COMM_RATE_MBIT", 200))
    ab["dcn_rate_mbit"] = rate
    _tc("qdisc", "del", "dev", "lo", "root")  # pre-clean a stale qdisc
    # generous burst/queue: an undersized tbf queue DROPS past the burst
    # and TCP's loss response collapses throughput unevenly across arms —
    # the A/B wants a clean bandwidth ceiling, not a lossy link
    shaped = _tc(
        "qdisc", "add", "dev", "lo", "root", "tbf",
        "rate", f"{rate}mbit", "burst", "1mb", "latency", "800ms",
    )
    if not shaped:
        ab["error"] = "tc/tbf unavailable: cannot shape a DCN-class link"
        _write_atomic(out_path, ab)
        return 0
    try:
        res, err = _grad_comm_world(
            2, {}, float(os.environ.get("BENCH_GRAD_COMM_TIMEOUT", 600))
        )
        if err is not None:
            ab["error"] = err
        else:
            ab.update(res)
            # bytes each arm puts on the shaped DCN per combine (2 hosts,
            # 1 device/host: the full tree crosses; the hier hop rides the
            # wire's sum dtype) — the engine records the same accounting
            # per epoch as comm_bytes_ici/comm_bytes_dcn
            elems = ab["tree_elems"]
            ab["flat_dcn_bytes"] = elems * 4
            for wire in ("fp32", "int8", "int4"):
                ab[f"hier_{wire}_dcn_bytes"] = (
                    elems * wirefmt.wire_payload_bytes(wire, 2)
                )
            if ab.get("hier_int8_wall_s"):
                ab["speedup_x"] = round(
                    ab["flat_wall_s"] / ab["hier_int8_wall_s"], 3
                )
                ab["speedup_int4_x"] = round(
                    ab["flat_wall_s"] / ab["hier_int4_wall_s"], 3
                )
                # the structure-only (fp32) ratio on a symmetric-per-hop
                # fabric shows WHY the gating probe exists: without a
                # compressed wire the extra hops can lose
                ab["speedup_fp32_x"] = round(
                    ab["flat_wall_s"] / ab["hier_fp32_wall_s"], 3
                )
    except Exception as e:  # noqa: BLE001 — the A/B must never leave lo shaped
        ab["error"] = repr(e)
    finally:
        if not _tc("qdisc", "del", "dev", "lo", "root"):
            sys.stderr.write("[bench] WARNING: failed to unshape lo\n")
    _write_atomic(out_path, ab)

    # ---- leg 3: 3-tier fabric at TWO shaped DCN rates (ISSUE 17) ----
    # Two gloo processes x 4 in-process devices = (dcn 2, host 2, device 2);
    # only the dcn hop rides the shaped loopback. Run the three arms at two
    # DCN classes (PR 12's bandwidth-bound point and a tighter link) — at
    # both, the cost model's per-hop codec must put the N-level wall at or
    # under the flat AND fixed-int8 two-level arms. Each rate is shaped
    # fresh and unshaped in a finally, same discipline as leg 2.
    rates3 = [
        int(r)
        for r in os.environ.get(
            "BENCH_GRAD_COMM_TIER3_RATES", "200,60"
        ).split(",")
        if r.strip()
    ]
    tier3 = {}
    for r3 in rates3:
        key = f"{r3}mbit"
        _tc("qdisc", "del", "dev", "lo", "root")
        if not _tc(
            "qdisc", "add", "dev", "lo", "root", "tbf",
            "rate", f"{r3}mbit", "burst", "1mb", "latency", "800ms",
        ):
            tier3[key] = {"error": "tc/tbf unavailable"}
            continue
        try:
            res, err = _grad_comm_world(
                2,
                {
                    "BENCH_GRAD_COMM_TIER3": "1",
                    "BENCH_GRAD_COMM_RATE_MBIT": str(r3),
                },
                float(os.environ.get("BENCH_GRAD_COMM_TIMEOUT", 600)),
            )
            if err is not None:
                tier3[key] = {"error": err}
            else:
                if res.get("tree3_wall_s"):
                    res["speedup_vs_flat_x"] = round(
                        res["flat_wall_s"] / res["tree3_wall_s"], 3
                    )
                    res["speedup_vs_hier2_x"] = round(
                        res["hier2_int8_wall_s"] / res["tree3_wall_s"], 3
                    )
                tier3[key] = res
        except Exception as e:  # noqa: BLE001 — never leave lo shaped
            tier3[key] = {"error": repr(e)}
        finally:
            if not _tc("qdisc", "del", "dev", "lo", "root"):
                sys.stderr.write("[bench] WARNING: failed to unshape lo\n")
        _write_atomic(out_path, {**ab, "tier3": tier3})
    ab["tier3"] = tier3
    _write_atomic(out_path, ab)
    return 0


def run_zero1_ab(out_path: str) -> int:
    """Sharded-vs-replicated weight-update A/B (ISSUE 13 acceptance field
    ``zero1_ab``), in a dedicated subprocess on a 4-device CPU mesh.

    Fixed batch by construction: both arms consume the SAME gradient tree
    (a resnet18-scale parameter tree, ~11M elements), so the delta is the
    update path alone. The sharded arm runs the SHIPPED ZeRO-1 spine
    (train/steps.py ``_zero1_update`` through the production shard_map
    spec) with adamw — the generic-optax contract, not the old SGD twin.
    Reported: per-device optimizer-state bytes (the ~1/N shrink at world
    4), best-of update walls and their ratio, and the obs per-device
    peak-memory snapshot (host-RSS fallback on this tier)."""
    done = _install_init_watchdog()
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dynamic_load_balance_distributeddnn_tpu.models import build_model
    from dynamic_load_balance_distributeddnn_tpu.obs.registry import (
        device_peak_memory,
    )
    from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
        data_mesh,
        shard_map,
    )
    from dynamic_load_balance_distributeddnn_tpu.train.state import (
        TrainState,
        shard_optimizer_state,
        zero1_padded_size,
    )
    from dynamic_load_balance_distributeddnn_tpu.train.steps import StepLibrary

    ab = {"optimizer": "adamw", "model": "resnet18"}
    mesh = data_mesh()
    n = len(mesh.devices.flat)
    ab["world"] = n
    spec = build_model("resnet18", num_classes=10)
    params = spec.module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3), jnp.float32),
        train=False,
    )
    elems = int(sum(p.size for p in jax.tree_util.tree_leaves(params)))
    ab["tree_elems"] = elems
    tx = optax.inject_hyperparams(optax.adamw)(
        learning_rate=1e-3, weight_decay=1e-2
    )
    padded = zero1_padded_size(params, n)
    rep = NamedSharding(mesh, P())
    params = jax.device_put(params, rep)
    grads = jax.tree_util.tree_map(lambda p: jnp.ones_like(p) * 1e-3, params)
    grads = jax.device_put(grads, rep)
    done.set()

    def dev_bytes(opt_state) -> int:
        """Optimizer-state bytes RESIDENT on device 0 (one shard of the
        chunked leaves, the full copy of replicated ones)."""
        dev0 = mesh.devices.flat[0]
        total = 0
        for leaf in jax.tree_util.tree_leaves(opt_state):
            for s in leaf.addressable_shards:
                if s.device == dev0:
                    total += int(s.data.nbytes)
        return total

    def timed(fn, *args, reps: int = 5) -> float:
        jax.block_until_ready(fn(*args))  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    # ---- replicated arm: per-leaf optax update, full state per device ----
    rep_state = TrainState(
        params=params, opt_state=jax.device_put(tx.init(params), rep),
        step=jax.device_put(jnp.zeros((), jnp.int32), rep),
    )
    ab["opt_bytes_per_device_replicated"] = dev_bytes(rep_state.opt_state)

    def replicated_step(state, g):
        updates, opt_state = tx.update(g, state.opt_state, state.params)
        p2 = optax.apply_updates(state.params, updates)
        return state.replace(params=p2, opt_state=opt_state, step=state.step + 1)

    f_rep = jax.jit(replicated_step)
    ab["update_wall_replicated_s"] = round(timed(f_rep, rep_state, grads), 6)

    # ---- sharded arm: the SHIPPED zero-1 spine (production code path,
    # via the production-owned shell factory) ----
    lib = StepLibrary.zero1_shell(mesh, tx, padded)
    sh_state = shard_optimizer_state(
        TrainState(
            params=params, opt_state=tx.init(params),
            step=jax.device_put(jnp.zeros((), jnp.int32), rep),
        ),
        mesh,
        tx,
    )
    ab["opt_bytes_per_device_sharded"] = dev_bytes(sh_state.opt_state)
    ab["state_bytes_shrink_x"] = round(
        ab["opt_bytes_per_device_replicated"]
        / max(ab["opt_bytes_per_device_sharded"], 1),
        3,
    )
    sspec = lib._state_spec()

    def sharded_step(state, g):
        return lib._zero1_update(
            state, g, jax.random.PRNGKey(0), with_comm=True
        )

    f_sh = jax.jit(
        shard_map(
            sharded_step,
            mesh=mesh,
            in_specs=(sspec, P()),
            out_specs=sspec,
            check_vma=False,
        )
    )
    ab["update_wall_sharded_s"] = round(timed(f_sh, sh_state, grads), 6)
    ab["update_wall_ratio_x"] = round(
        ab["update_wall_replicated_s"] / max(ab["update_wall_sharded_s"], 1e-9),
        3,
    )
    ab["memory"] = device_peak_memory()
    # honest framing for the CPU tier: the replicated update pays NO
    # collective (state is local), so the sharded arm's reduce-scatter +
    # all-gather read as pure overhead here; on real ICI the collective
    # amortizes and the 1/N state shrink is the point (arXiv 2004.13336)
    ab["note"] = (
        "single-host CPU mesh: update_wall_ratio_x < 1 reflects collective "
        "cost with no memory pressure; the acceptance datum is the ~1/N "
        "state_bytes_shrink_x at fixed batch"
    )
    _write_atomic(out_path, ab)
    return 0


def run_multistream_ab(out_path: str) -> int:
    """K-small-jobs sequential vs multiplexed A/B (ISSUE 18 acceptance
    field ``multistream_ab``), in a dedicated subprocess on an 8-device
    CPU mesh.

    Each job is a SMALL tenant by construction — a 2-worker world pinned
    to its own device pair, the shape a training service actually receives
    (a tiny job cannot feed the whole pool: past a few devices its
    marginal product is ~0 in dispatch/collective overhead). Arm A
    (sequential): the K jobs run one after another — the one-job-at-a-time
    service shape, 6 of 8 devices idle at any moment. Arm B (multiplexed):
    the SAME K JobSpecs submitted to one ``MultiStreamEngine``; the outer
    solve packs all K onto the pool and they run concurrently on disjoint
    device pairs. Total examples, epochs, and per-job compile lineage are
    identical by construction (fresh trainer per job in both arms).

    Reported: per-arm total wall, aggregate examples/s, ``speedup_x``
    (sequential / multiplexed, acceptance >= 1.2), per-job makespans, and
    the multiplexed arm's device-idle fraction."""
    done = _install_init_watchdog()
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from dynamic_load_balance_distributeddnn_tpu.config import Config
    from dynamic_load_balance_distributeddnn_tpu.data.datasets import (
        synthetic_dataset,
    )
    from dynamic_load_balance_distributeddnn_tpu.runtime.scheduler import (
        JobSpec,
        MultiStreamEngine,
    )
    from dynamic_load_balance_distributeddnn_tpu.train import Trainer

    n_jobs = int(os.environ.get("BENCH_MULTISTREAM_JOBS", 4))
    n_epochs = int(os.environ.get("BENCH_MULTISTREAM_EPOCHS", 3))
    n_train = int(os.environ.get("BENCH_MULTISTREAM_NTRAIN", 512))
    pool = len(jax.devices())
    per_job = max(pool // n_jobs, 1)
    ab = {
        "jobs": n_jobs,
        "epochs_per_job": n_epochs,
        "n_train": n_train,
        "pool_devices": pool,
        "devices_per_job": per_job,
        "model": "mnistnet",
    }
    bundle = synthetic_dataset("mnist", n_train=n_train, n_test=256)
    work_dir = tempfile.mkdtemp(prefix="multistream_ab_")

    def job_cfg(i: int, arm: str) -> Config:
        return Config(
            debug=True,
            world_size=per_job,
            # the tenant's own device pair — the pool ordinals the outer
            # solve hands job i at equal demand (keep-phase + sorted free
            # draw), so admission rides the no-op allotment path in arm B
            # and arm A runs the identical world shape
            device=[per_job * i + d for d in range(per_job)],
            batch_size=64,
            learning_rate=0.05,
            epoch_size=n_epochs,
            dataset="mnist",
            model="mnistnet",
            dynamic_batch_size=False,
            seed=100 + i,
            bucket=8,
            stat_dir=os.path.join(work_dir, f"{arm}_job{i}"),
        )

    done.set()

    # ---- arm A: sequential, each job alone on the full pool ----
    serial_walls = []
    t0 = time.perf_counter()
    for i in range(n_jobs):
        t_job = time.perf_counter()
        Trainer(job_cfg(i, "seq"), bundle=bundle, log_to_file=False).run()
        serial_walls.append(round(time.perf_counter() - t_job, 3))
    ab["sequential_wall_s"] = round(time.perf_counter() - t0, 3)
    ab["sequential_job_walls_s"] = serial_walls
    _write_atomic(out_path, ab)

    # ---- arm B: the same jobs multiplexed over one pool ----
    eng = MultiStreamEngine(n_devices=pool)
    for i in range(n_jobs):
        eng.submit(
            JobSpec(
                f"job{i}",
                job_cfg(i, "ms"),
                bundle=bundle,
                max_devices=per_job,
            )
        )
    t0 = time.perf_counter()
    jobs = eng.run()
    ab["multiplexed_wall_s"] = round(time.perf_counter() - t0, 3)
    st = eng.stats()
    ab["multiplexed_makespans_s"] = {
        j: round(info["makespan_s"], 3) for j, info in st["jobs"].items()
    }
    ab["multiplexed_device_idle_fraction"] = (
        round(st["device_idle_fraction"], 4)
        if st["device_idle_fraction"] is not None
        else None
    )
    ab["multiplexed_migrations"] = st["migrations"]
    ab["all_jobs_done"] = all(
        js.status == "done" for js in jobs.values()
    )

    examples = float(n_jobs * n_epochs * n_train)
    ab["sequential_examples_per_s"] = round(
        examples / max(ab["sequential_wall_s"], 1e-9), 1
    )
    ab["multiplexed_examples_per_s"] = round(
        examples / max(ab["multiplexed_wall_s"], 1e-9), 1
    )
    ab["speedup_x"] = round(
        ab["sequential_wall_s"] / max(ab["multiplexed_wall_s"], 1e-9), 3
    )
    ab["meets_1_2x"] = bool(ab["speedup_x"] >= 1.2)
    ab["note"] = (
        f"{n_jobs} small ({per_job}-worker) mnistnet jobs over one "
        f"{pool}-device pool: the sequential arm runs them one at a time "
        f"({pool - per_job} devices idle throughout); the engine packs "
        f"all {n_jobs} concurrently on disjoint slices"
    )
    _write_atomic(out_path, ab)
    return 0


def _steady(walls_off, walls_on):
    """Steady-state epoch-wall windows. Off arm: skip epoch 0 (calibration,
    no injection). On arm: skip epoch 0 AND epoch 1 — epoch 1 is injected but
    still on uniform shares (its rebalance consumed epoch-0 uninjected
    times), so it is an off-arm epoch in disguise. With the off arm running
    one epoch fewer (run_arms), both windows hold epochs-2 samples (>= 5 at
    the default BENCH_EPOCHS=7). Injection strength is constant across
    counted epochs because the injector calibrates to the requested factors
    BEFORE the first injected epoch (engine._calibrate_iter_cost); run_arms
    records the calibration flag per arm and _result_from refuses to build a
    result from an arm whose flag is explicitly False."""
    off = walls_off[1:] if len(walls_off) >= 2 else []
    on = walls_on[2:] if len(walls_on) >= 3 else []
    return off, on


def _stats(window) -> dict | None:
    """Dispersion-robust summary of one arm's steady window: the headline is
    the MEDIAN (tunnel/host jitter swings single epochs 30-40%, VERDICT r2
    weak #2 — a min over 2-4 samples cannot resolve a 10-30% effect); min and
    IQR ride along so the spread is visible in the artifact."""
    import numpy as np

    if not window:
        return None
    w = np.asarray(window, dtype=np.float64)
    q1, q3 = np.percentile(w, [25, 75])
    return {
        "median": float(np.median(w)),
        "min": float(np.min(w)),
        "iqr": float(q3 - q1),
        "n": int(w.size),
    }


def _result_from(partial) -> dict | None:
    off_w, on_w = _steady(partial.get("off", []), partial.get("on", []))
    off, on = _stats(off_w), _stats(on_w)
    if off is None or on is None or on["median"] <= 0:
        return None
    instr = partial.get("instr", {})
    for arm in ("off", "on"):
        if instr.get(f"{arm}_injection_calibrated") is False:
            # uncalibrated injection ramps across epochs — the arms would be
            # compared at different injection strengths (VERDICT r2 weak #2);
            # such a run is not a result (missing key = legacy partial, allowed)
            sys.stderr.write(
                f"[bench] arm {arm} ran without injection calibration; "
                "discarding its A/B\n"
            )
            return None
    # Theoretical balancer ceiling on a single timeshared chip (all workers'
    # steps serialize): uniform-share cost Σ(f_i)/ws over equilibrium cost
    # Σ(k·f_i/f_i)=ws·k with k=1/Σ(1/f_i). For [3,1,1,1]: 1.5/1.2 = 1.25x.
    # vs_baseline should be judged against this, not the parallel-worker
    # ceiling (Σf_i/ws / max-balanced = 1.5x here) the paper's multi-GPU
    # setting allows. See artifacts/AB_ANALYSIS.md.
    ws = int(partial.get("world_size") or 4)
    # read the factors the injector actually ran with (persisted by
    # run_arms); the fallback only serves legacy partials
    factors = [float(f) for f in partial.get("straggler_factors") or []]
    if len(factors) != ws:
        factors = [3.0] + [1.0] * (ws - 1)
    uniform_cost = sum(factors) / ws
    eq_cost = ws / sum(1.0 / f for f in factors)
    detail = {
        "backend": partial.get("backend"),
        "model": partial.get("model"),
        # a resumed arm was measured when its partial was SAVED, not when the
        # result was finally assembled — stamp the older of the two so the
        # cache TTL bounds true measurement age
        "measured_at_unix": round(
            min(time.time(), float(partial.get("saved_at") or time.time())), 1
        ),
        "serialized_chip_ceiling": round(uniform_cost / eq_cost, 4),
        # nominal (requested) injection profile; the REALIZED device-compute
        # profile rides in via instr ({arm}_realized_injection_profile) so
        # both are always printed together — a speedup past the nominal
        # ceiling must show a realized profile that explains it
        "nominal_injection_profile": factors,
        "dbs_off_epochs_s": partial.get("off"),
        "dbs_on_epochs_s": partial.get("on"),
        "off_steady": off,
        "on_steady": on,
        "vs_baseline_min": round(off["min"] / on["min"], 4) if on["min"] > 0 else None,
        "clean_fused_epochs_s": partial.get("clean"),
        "n_train": partial.get("n_train"),
        "world_size": partial.get("world_size"),
        **partial.get("instr", {}),
    }
    # Modeled-parallel A/B (see run_arms: max per-worker compute seconds per
    # epoch, the ws-chip deployment frame — ceiling for [3,1,1,1] is
    # (max f/ws)/(1/Σ(1/f)) = 0.75/0.3 = 2.5x there, vs the serialized
    # 1.25x above).
    instr_all = partial.get("instr", {})
    pwo, pwn = _steady(
        instr_all.get("off_parallel_walls_s") or [],
        instr_all.get("on_parallel_walls_s") or [],
    )
    so, sn = _stats([w for w in pwo if w]), _stats([w for w in pwn if w])
    if so and sn and sn["median"] > 0:
        detail["modeled_parallel"] = {
            "off_steady": so,
            "on_steady": sn,
            "speedup_median": round(so["median"] / sn["median"], 4),
            "note": "per-worker device-seconds maxima (probe-based), the "
            "multi-chip deployment frame; the headline vs_baseline stays "
            "in the measured serialized-wall frame",
        }
    return {
        "metric": "densenet121_cifar10_ws4_3to1straggler_epoch_wallclock"
        if partial.get("backend") == "tpu"
        else "cpu_fallback_ws4_3to1straggler_epoch_wallclock",
        "value": round(on["median"], 4),
        "unit": "s",
        "vs_baseline": round(off["median"] / on["median"], 4),
        "detail": detail,
    }


def _emit_and_exit(signum=None, frame=None):
    if _best_result is not None:
        print(json.dumps(_best_result), flush=True)
        sys.exit(0)
    sys.exit(1)


def _run_child(args, timeout):
    try:
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + args,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None


def _wait_healthy(deadline: float) -> bool:
    """Quick preflights until the runtime answers or the deadline passes.
    After a mid-run outage (e.g. the remote-compile tunnel dropping), retrying
    arms against a dead runtime just burns budget; a 1-matmul preflight is
    cheap insurance."""
    while time.time() < deadline:
        cap = min(300.0, deadline - time.time())
        if cap < 30:
            return False
        proc = _run_child(["--preflight"], timeout=cap)
        if proc is not None and proc.returncode == 0:
            return True
        rc = "timeout" if proc is None else proc.returncode
        sys.stderr.write(f"[bench] health re-check failed (rc={rc}); waiting\n")
        time.sleep(30)
    return False


def _try_arms(force_cpu: bool, deadline: float, retries: int) -> dict | None:
    """Run the arms subprocess with retries; returns a result dict (possibly
    from salvaged partials) or None. Partials carry across attempts: a retry
    resumes completed arms instead of re-running them."""
    best = None
    best_quality = (-1, -1)  # (epochs salvaged, n_train) — bigger is better
    n_train = int(os.environ.get("BENCH_NTRAIN", 12800))
    epochs = max(int(os.environ.get("BENCH_EPOCHS", 7)), 4)
    arm_needs = {"off": max(3, epochs - 1), "on": epochs}  # mirrors run_arms
    # completed-arm partials persist OUTSIDE this invocation: a tunnel window
    # long enough for one arm but not both must not force the next window
    # (a fresh bench.py run, e.g. the queue's retry) to re-run the finished
    # arm. run_arms validates backend/model/n_train before resuming, so a
    # stale file is safely ignored.
    stable_partial = os.environ.get(
        "BENCH_PARTIAL_PATH",
        os.path.join("artifacts", f".bench_partial_{'cpu' if force_cpu else 'tpu'}.json"),
    )
    resume_path = ""
    shrink = 0
    prev = None
    if os.path.exists(stable_partial):
        try:
            with open(stable_partial) as f:
                prev = json.load(f)
        except (OSError, ValueError):
            prev = None
    if prev is not None:
        # seed only from a file run_arms will actually resume, at whatever
        # point on the shrink ladder it was saved (a partial completed after
        # a shrink must resume AT that n_train, not be rejected); bound its
        # age so timings from an old session never pair with fresh ones
        backend = "cpu_fallback" if force_cpu else "tpu"
        exp_model = "mnistnet" if force_cpu else "densenet"
        ttl = float(os.environ.get("BENCH_PARTIAL_TTL_S", 86400))
        fresh = (time.time() - float(prev.get("saved_at") or 0)) < ttl
        has_arm = any(len(prev.get(a, []) or []) >= n for a, n in arm_needs.items())
        ladder = (
            [int(os.environ.get("BENCH_CPU_NTRAIN", 2048))]
            if force_cpu
            else [max(n_train // (2**k), 2560) for k in range(max(retries, 1))]
        )
        seeded = False
        if fresh and has_arm:
            for k, nt in enumerate(ladder):
                if _resume_compatible(prev, backend, exp_model, nt):
                    resume_path = stable_partial
                    if not force_cpu:
                        shrink = k
                    seeded = True
                    break
        if not seeded:
            # stale or incompatible: delete it, or a later invocation that
            # happens to match could resume timings from another session
            try:
                os.unlink(stable_partial)
            except OSError:
                pass
    for attempt in range(retries):
        budget = deadline - time.time()
        if budget < 120:
            break
        if attempt > 0 and not force_cpu:
            if not _wait_healthy(deadline - 60):
                break
        fd, out_path = tempfile.mkstemp(suffix=".json")
        os.close(fd)
        # Salvage by shrinking — but never away from a resumable partial:
        # a completed arm is only reusable at the same n_train.
        env_n = str(max(n_train // (2 ** shrink), 2560))
        os.environ["BENCH_NTRAIN"] = env_n
        args = ["--arms", "--out", out_path] + (["--cpu"] if force_cpu else [])
        if resume_path:
            args += ["--resume", resume_path]
        t0 = time.time()
        proc = _run_child(args, timeout=budget)
        rc = "timeout" if proc is None else proc.returncode
        try:
            with open(out_path) as f:
                partial = json.load(f)
        except Exception:
            partial = {}
        res = _result_from(partial)
        calib_rejected = False
        if res is None:
            # No result from this attempt: promoting a known-rejected arm
            # would make every later invocation resume — and re-reject — it
            # for the whole partial TTL, pinning bench to no-result (or
            # burning a full window measuring its sibling first). Strip
            # completed-but-uncalibrated arms REGARDLESS of how the attempt
            # ended; additionally, an rc==0 run whose rejection is not
            # attributable to an arm is dropped wholesale.
            instr = partial.get("instr", {})
            poisoned = [
                a
                for a in ("off", "on")
                if instr.get(f"{a}_injection_calibrated") is False
                and len(partial.get(a, [])) >= arm_needs[a]
            ]
            all_complete = all(
                len(partial.get(a, [])) >= n for a, n in arm_needs.items()
            )
            clean_exit = proc is not None and proc.returncode == 0
            if poisoned or (all_complete and clean_exit):
                # the no-shrink exemption only holds when the attempt itself
                # finished: a timeout/crash mid-sibling-arm still means the
                # budget may be the problem, so the ladder stays armed
                calib_rejected = clean_exit
                for a in poisoned:
                    partial.pop(a, None)
                    (partial.get("arm_saved_at") or {}).pop(a, None)
                    for k in [k for k in list(instr) if k.startswith(a + "_")]:
                        instr.pop(k)
                if not poisoned:
                    partial = {}
                # the file-level stamp may have belonged to a stripped arm;
                # recompute it from the surviving resumed arms so a fresh
                # survivor is not promoted pre-aged (it would expire the
                # partial TTL and the result cache early)
                arm_ts = list((partial.get("arm_saved_at") or {}).values())
                if arm_ts:
                    partial["saved_at"] = min(arm_ts)
                else:
                    partial.pop("saved_at", None)
                # persist the strip: the on-disk out_path still holds the
                # rejected arms, and the promotion-FAILURE fallback below
                # resumes from out_path — it must not see them either
                _write_atomic(out_path, partial)
                # the file that seeded this attempt holds the rejected arms;
                # drop it so nothing can resume them verbatim (a surviving
                # good arm is re-promoted just below)
                if resume_path:
                    try:
                        os.unlink(resume_path)
                    except OSError:
                        pass
                    resume_path = ""
        if res is not None:
            quality = (
                len(partial.get("off", [])) + len(partial.get("on", [])),
                int(partial.get("n_train") or 0),
            )
            if quality > best_quality:  # keep the best salvage, not the latest
                best, best_quality = res, quality
            if proc is not None and proc.returncode == 0:
                for p in (out_path, resume_path):
                    if p:
                        try:
                            os.unlink(p)
                        except OSError:
                            pass
                return best
        # Keep this attempt's partial ONLY if a whole arm completed — that is
        # what run_arms can actually resume (it requires >= n_ep epochs).
        completed_arm = any(
            len(partial.get(a, [])) >= n for a, n in arm_needs.items()
        )
        if completed_arm:
            # promote to the stable path so the NEXT bench invocation (a
            # later tunnel window) resumes it too; on promotion failure
            # (unwritable artifacts/), fall back to the live tempfile so
            # THIS invocation still resumes correctly
            try:
                os.makedirs(os.path.dirname(stable_partial) or ".", exist_ok=True)
                stamped = dict(partial)
                # never re-stamp forward: a partial resumed across windows
                # keeps the save time of its OLDEST constituent arm, so the
                # partial TTL and measured_at_unix bound true age
                stamped["saved_at"] = min(
                    float(partial.get("saved_at") or time.time()), time.time()
                )
                _write_atomic(stable_partial, stamped)
                if out_path != stable_partial:
                    os.unlink(out_path)
                resume_path = stable_partial
            except OSError:
                resume_path = out_path
        else:
            try:
                os.unlink(out_path)
            except OSError:
                pass
            if not resume_path and not calib_rejected:
                # nothing salvageable anywhere — next attempt runs smaller.
                # (Never shrink while a resumable partial exists: resume
                # requires the same n_train. And never shrink because of a
                # calibration rejection: the run FIT the budget — scale was
                # not the problem.)
                shrink += 1
        sys.stderr.write(
            f"[bench] arms(cpu={force_cpu}) attempt {attempt+1} rc={rc} "
            f"({time.time()-t0:.0f}s, ntrain={env_n}); partial epochs "
            f"off={len(partial.get('off', []))} on={len(partial.get('on', []))}\n"
        )
        if proc is not None and proc.stderr:
            sys.stderr.write(proc.stderr[-1500:] + "\n")
    # retries exhausted / budget out: leave the stable partial in place —
    # the next bench invocation (another tunnel window) resumes it
    return best


def _result_file_path() -> str:
    return os.environ.get(
        "BENCH_RESULT_PATH", os.path.join("artifacts", "BENCH_result.json")
    )


def _write_result_file(res: dict) -> None:
    """Best-known result mirrored to disk the moment it exists. The driver's
    capture must survive an rc=124 kill at ANY point — round 5 shipped
    `rc=124, parsed: null` while a fresh on-chip result sat in the cache
    because nothing was written (or printable) until the arms finished."""
    try:
        os.makedirs(os.path.dirname(_result_file_path()) or ".", exist_ok=True)
        _write_atomic(_result_file_path(), res)
    except OSError:
        pass


def _publish(res: dict) -> None:
    """Adopt ``res`` as the best-known result AND print it as a JSON line
    NOW. The driver parses the LAST JSON line on stdout, so publishing every
    improvement the moment it exists guarantees the best disk-derivable
    result is already emitted before the preflight ladder / arms can eat the
    budget — an rc=124 kill at ANY later point (even SIGKILL after the
    grace, where the SIGTERM handler never runs) still leaves a parsed
    line. A better result printed later simply becomes the new last line."""
    global _best_result
    _best_result = res
    _write_result_file(res)
    print(json.dumps(res), flush=True)


def _preflight_seed() -> "tuple[dict | None, str]":
    """Best result derivable from disk BEFORE any preflight/arm runs:
    the age-bounded cached on-chip artifact, else a result assembled from a
    completed partial (TPU first, then the CPU tier's rows). Returns
    (result, source) with source in {"cached_tpu", "partial_tpu",
    "partial_cpu", ""}."""
    res = _cached_tpu_result()
    if res is not None:
        return res, "cached_tpu"
    ttl = float(os.environ.get("BENCH_PARTIAL_TTL_S", 86400))
    for tier in ("tpu", "cpu"):
        path = os.environ.get(
            "BENCH_PARTIAL_PATH",
            os.path.join("artifacts", f".bench_partial_{tier}.json"),
        )
        try:
            with open(path) as f:
                prev = json.load(f)
        except (OSError, ValueError):
            continue
        if (time.time() - float(prev.get("saved_at") or 0)) > ttl:
            continue
        res = _result_from(prev)
        if res is not None:
            res["detail"]["salvaged_from"] = path
            # label from the partial's OWN backend, not the path loop: with
            # BENCH_PARTIAL_PATH set both tiers share one file, and a CPU
            # partial mislabeled "partial_tpu" would gate off the fresh CPU
            # insurance arms in main()
            src = "partial_tpu" if prev.get("backend") == "tpu" else "partial_cpu"
            return res, src
    return None, ""


def _cached_tpu_result() -> dict | None:
    """Last successful ON-CHIP result, for when the tunnel is down at
    invocation time (it comes and goes for hours here). A real measured
    number from this round beats re-measuring on the CPU fallback — the
    result is clearly labeled cached (cached_result/cached_from/
    cached_age_s in detail) and age-bounded so a previous round's artifact
    can never masquerade as current."""
    path = os.environ.get(
        "BENCH_CACHE_PATH", os.path.join("artifacts", "BENCH_local_tpu.json")
    )
    ttl = float(os.environ.get("BENCH_CACHE_TTL_S", 48 * 3600))
    try:
        with open(path) as f:
            res = json.load(f)
        if res.get("detail", {}).get("backend") != "tpu":
            return None
        # the timestamp must come from INSIDE the artifact: git checkout
        # refreshes file mtimes, which would let a PREVIOUS round's
        # committed artifact (measured on old code) pass an mtime TTL.
        # Legacy artifacts without the stamp are rejected outright.
        ts = res["detail"].get("measured_at_unix")
        if not ts:
            return None
        age = time.time() - float(ts)
        if age > ttl or age < -60:
            return None
        res["detail"]["cached_result"] = True
        res["detail"]["cached_from"] = path
        res["detail"]["cached_age_s"] = round(age, 1)
        return res
    except (OSError, ValueError, TypeError, AttributeError):
        return None


def main() -> int:
    global _best_result
    if "--preflight" in sys.argv:
        return run_preflight(light="--light" in sys.argv)
    if "--aot-ab" in sys.argv:
        return run_aot_ab(sys.argv[sys.argv.index("--out") + 1])
    if "--workers-ab" in sys.argv:
        return run_workers_ab(sys.argv[sys.argv.index("--out") + 1])
    if "--grad-comm-ab" in sys.argv:
        return run_grad_comm_ab(sys.argv[sys.argv.index("--out") + 1])
    if "--zero1-ab" in sys.argv:
        return run_zero1_ab(sys.argv[sys.argv.index("--out") + 1])
    if "--multistream-ab" in sys.argv:
        return run_multistream_ab(sys.argv[sys.argv.index("--out") + 1])
    if "--grad-comm-worker" in sys.argv:
        i = sys.argv.index("--grad-comm-worker")
        return run_grad_comm_worker(
            int(sys.argv[i + 1]), int(sys.argv[i + 2]), int(sys.argv[i + 3])
        )
    if "--arms" in sys.argv:
        out_path = sys.argv[sys.argv.index("--out") + 1]
        resume = (
            sys.argv[sys.argv.index("--resume") + 1]
            if "--resume" in sys.argv
            else ""
        )
        return run_arms(out_path, force_cpu="--cpu" in sys.argv, resume_path=resume)

    signal.signal(signal.SIGTERM, _emit_and_exit)
    signal.signal(signal.SIGINT, _emit_and_exit)

    t_start = time.time()
    deadline = t_start + float(os.environ.get("BENCH_TOTAL_BUDGET", 5400))
    reserve = float(os.environ.get("BENCH_ARM_RESERVE", 1800))
    retries = int(os.environ.get("BENCH_RETRIES", 3))
    force_cpu = os.environ.get("BENCH_FORCE_CPU") == "1"
    insurance_on = os.environ.get("BENCH_CPU_INSURANCE", "1") == "1"

    if force_cpu:
        res = _try_arms(force_cpu=True, deadline=deadline, retries=retries)
        if res is None:
            sys.stderr.write("[bench] no result obtained\n")
            return 1
        _publish(res)
        return 0

    # Pre-capture BEFORE the preflight ladder (which can eat the whole driver
    # budget waiting on a wedged backend): the best disk-derivable result is
    # written to the result file AND EMITTED as a parsed JSON line right
    # away, so a driver kill at any later point — SIGTERM (handled) or
    # SIGKILL (not handleable) — still leaves this round's best capture as
    # the final parsed line instead of `parsed: null`.
    seeded, seed_src = _preflight_seed()
    if seeded is not None:
        _publish(seeded)
        sys.stderr.write(f"[bench] pre-captured fallback result ({seed_src})\n")
    else:
        # Cold start, nothing derivable from disk (rounds 4 and 5): the
        # "every improvement prints a JSON line immediately" guarantee had
        # no FIRST line to fall back on, so an rc=124 kill inside the
        # preflight ladder left `parsed: null`. Emit an explicit floor NOW —
        # the driver always parses something; any later result supersedes it
        # as the new last line. Deliberately NOT stored in _best_result: the
        # floor must not gate off the insurance arms or the cached-artifact
        # fallbacks below, which all key on "no real result yet".
        floor = {
            "status": "no_result",
            "detail": {"reason": "pre-preflight floor; no prior artifact on disk"},
        }
        _write_result_file(floor)
        print(json.dumps(floor), flush=True)
        sys.stderr.write("[bench] no disk-derivable seed; emitted no_result floor\n")

    tpu_ok = False
    ladder = [
        float(x)
        for x in os.environ.get(
            "BENCH_PREFLIGHT_TIMEOUTS", "600,1500,2400"
        ).split(",")
    ]
    i = 0
    while time.time() < deadline - reserve:
        cap = ladder[min(i, len(ladder) - 1)]
        cap = min(cap, deadline - reserve - time.time())
        if cap < 60:
            break
        sys.stderr.write(f"[bench] preflight attempt {i+1} (cap {cap:.0f}s)\n")
        # attempt 1 runs the shrunk profile: init-watchdog capped inside the
        # attempt budget, no matmul compile (see run_preflight) — a cold
        # cache + slow first contact can no longer eat the whole first rung
        proc = _run_child(["--preflight"] + (["--light"] if i == 0 else []), timeout=cap)
        if proc is not None and proc.returncode == 0:
            sys.stderr.write(f"[bench] preflight ok: {proc.stdout.strip()}\n")
            tpu_ok = True
            break
        rc = "timeout" if proc is None else proc.returncode
        sys.stderr.write(f"[bench] preflight failed (rc={rc})\n")
        if (
            i == 0
            and insurance_on
            # a pre-seeded CPU-partial result is stale by definition — a
            # fresh insurance run still beats it; only a real on-chip
            # capture makes the insurance not worth its wall-clock
            and (_best_result is None or seed_src == "partial_cpu")
            and _cached_tpu_result() is None
        ):
            sys.stderr.write("[bench] running CPU insurance arms\n")
            fresh = _try_arms(
                force_cpu=True,
                deadline=min(time.time() + 1500, deadline),
                retries=1,
            )
            if fresh is not None:
                seed_src = ""
                _publish(fresh)
        i += 1
        time.sleep(30)

    if tpu_ok:
        res = _try_arms(force_cpu=False, deadline=deadline, retries=retries)
        if res is not None:
            _publish(res)  # a TPU number beats any insurance/seed
    if _best_result is None or _best_result.get("detail", {}).get("backend") != "tpu":
        cached = _cached_tpu_result()
        if cached is not None:
            sys.stderr.write(
                "[bench] tunnel unavailable for a live run; emitting the "
                f"cached on-chip result ({cached['detail']['cached_age_s']:.0f}s old, "
                f"{cached['detail']['cached_from']})\n"
            )
            _publish(cached)
    if _best_result is None and insurance_on:
        res = _try_arms(
            force_cpu=True, deadline=max(deadline, time.time() + 900), retries=1
        )
        if res is not None:
            _publish(res)
    if _best_result is None:
        sys.stderr.write("[bench] no result obtained\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
