#!/usr/bin/env python
"""Headline benchmark — prints ONE JSON line.

The north-star scenario (BASELINE.json / README.md:23-28): DenseNet-121 on
CIFAR-10, world_size=4, global batch 512, under an induced 3:1 straggler on
worker 0, DBS on vs off (A/B, as run.sh does). The straggler is delivered as
real on-device compute (fault_mode='compute'), so epoch wall-clock genuinely
moves; both arms run the same elastic execution path, so the comparison
isolates the balancer.

Each arm runs in its own subprocess with retries: a TPU runtime/tunnel crash
(observed sporadically on this host) kills only that attempt, not the
benchmark.

Metric: steady-state epoch wall-clock with DBS on (seconds; lower is better).
vs_baseline: speedup over the DBS-off arm (>1 means DBS wins).

Environment knobs: BENCH_NTRAIN (default 12800), BENCH_EPOCHS (default 5),
BENCH_WS (default 4), BENCH_RETRIES (default 4), BENCH_ARM_TIMEOUT (seconds
per arm attempt, default 5400), BENCH_INIT_TIMEOUT (seconds for TPU backend
init before the arm aborts, default 300).
"""

import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "./.jax_cache")


def run_arm(dbs_on: bool, n_epochs: int, out_path: str) -> None:
    """Subprocess entry: run one A/B arm and dump per-epoch walls to JSON."""
    # Fail fast if the TPU runtime/tunnel is wedged: backend init has been
    # observed to hang indefinitely after a TPU worker crash. A hung init
    # should cost one retry (with backoff), not the whole arm timeout. The
    # hang is inside PJRT C++ code, where Python signal handlers never run —
    # so the watchdog is a daemon thread that hard-exits the process.
    import threading

    init_done = threading.Event()

    def _watchdog():
        if not init_done.wait(int(os.environ.get("BENCH_INIT_TIMEOUT", 300))):
            sys.stderr.write("[bench] TPU backend init timed out; aborting arm\n")
            sys.stderr.flush()
            os._exit(17)

    threading.Thread(target=_watchdog, daemon=True).start()
    import jax

    jax.devices()
    init_done.set()

    from dynamic_load_balance_distributeddnn_tpu.config import Config
    from dynamic_load_balance_distributeddnn_tpu.data import load_dataset
    from dynamic_load_balance_distributeddnn_tpu.faults import StaticStragglerInjector
    from dynamic_load_balance_distributeddnn_tpu.train import Trainer

    n_train = int(os.environ.get("BENCH_NTRAIN", 12800))
    ws = int(os.environ.get("BENCH_WS", 4))
    bundle = load_dataset("cifar10", n_train=n_train, n_test=512)
    factors = [3.0] + [1.0] * (ws - 1)

    cfg = Config(
        debug=False,
        world_size=ws,
        batch_size=512,
        learning_rate=0.01,
        epoch_size=n_epochs,
        dataset="cifar10",
        model="densenet",
        dynamic_batch_size=dbs_on,
        fault_tolerance=True,
        fault_mode="compute",
        bucket=32,
    )
    tr = Trainer(
        cfg,
        bundle=bundle,
        injector=StaticStragglerInjector(factors, mode="compute"),
        log_to_file=False,
    )
    walls = [tr.run_epoch(e)["epoch_wall"] for e in range(n_epochs)]
    with open(out_path, "w") as f:
        json.dump({"walls": walls}, f)


def run_arm_with_retries(dbs_on: bool, n_epochs: int, retries: int):
    for attempt in range(retries):
        with tempfile.NamedTemporaryFile(
            mode="r", suffix=".json", delete=False
        ) as tf:
            out_path = tf.name
        try:
            proc = subprocess.run(
                [
                    sys.executable,
                    os.path.abspath(__file__),
                    "--arm",
                    "on" if dbs_on else "off",
                    "--epochs",
                    str(n_epochs),
                    "--out",
                    out_path,
                ],
                capture_output=True,
                text=True,
                timeout=int(os.environ.get("BENCH_ARM_TIMEOUT", 5400)),
            )
            if proc.returncode == 0:
                with open(out_path) as f:
                    return json.load(f)["walls"]
            sys.stderr.write(
                f"[bench] arm dbs={dbs_on} attempt {attempt + 1} failed "
                f"(rc={proc.returncode}):\n{proc.stderr[-2000:]}\n"
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"[bench] arm dbs={dbs_on} attempt {attempt + 1} timed out\n"
            )
        finally:
            try:
                os.unlink(out_path)
            except OSError:
                pass
        if attempt < retries - 1:
            # progressive backoff: a crashed TPU runtime/tunnel can take
            # minutes to come back (observed on this host)
            time.sleep(min(60 * (attempt + 1), 240))
    raise RuntimeError(f"arm dbs={dbs_on} failed after {retries} attempts")


def main() -> int:
    import numpy as np

    if "--arm" in sys.argv:
        i = sys.argv.index("--arm")
        dbs_on = sys.argv[i + 1] == "on"
        n_epochs = int(sys.argv[sys.argv.index("--epochs") + 1])
        out_path = sys.argv[sys.argv.index("--out") + 1]
        run_arm(dbs_on, n_epochs, out_path)
        return 0

    # epoch 0: calibration (no injection); epoch 1: first injected epoch;
    # 2+: DBS reaction — the minimum meaningful A/B needs 4 on-arm epochs
    epochs = max(int(os.environ.get("BENCH_EPOCHS", 5)), 4)
    retries = int(os.environ.get("BENCH_RETRIES", 4))

    # Epoch 0 of each arm is injection-free (cost calibration) and epoch 1 is
    # the first injected epoch; steady state is the tail.
    walls_off = run_arm_with_retries(False, max(3, epochs - 2), retries)
    walls_on = run_arm_with_retries(True, epochs, retries)
    off_steady = float(np.min(walls_off[1:]))
    on_steady = float(np.min(walls_on[2:]))
    speedup = off_steady / on_steady

    print(
        json.dumps(
            {
                "metric": "densenet121_cifar10_ws4_3to1straggler_epoch_wallclock",
                "value": round(on_steady, 4),
                "unit": "s",
                "vs_baseline": round(speedup, 4),
                "detail": {
                    "dbs_off_epochs_s": [round(w, 4) for w in walls_off],
                    "dbs_on_epochs_s": [round(w, 4) for w in walls_on],
                    "n_train": int(os.environ.get("BENCH_NTRAIN", 12800)),
                    "world_size": int(os.environ.get("BENCH_WS", 4)),
                },
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
